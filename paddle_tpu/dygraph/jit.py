"""dygraph.jit: to_static / TracedLayer / fused training steps.

Parity with reference python/paddle/fluid/dygraph/jit.py +
dygraph_to_static/: where the reference translates Python AST to a static
Program, the TPU design traces the SAME eager code with jax tracers (the tape
runs the identical registered functionals), producing one fused XLA
computation. `TrainStep` additionally folds grad + optimizer update into that
single program — the production training path used by the benchmarks.
"""
from __future__ import annotations

import contextlib
import functools
import weakref

import numpy as np
import jax
import jax.numpy as jnp

from .. import observability as _obs
from ..resilience import watchdog as _watchdog
from .tape import Tensor, Parameter, no_grad_guard
from .layers import Layer


@contextlib.contextmanager
def _bind(tensors: dict, values: dict):
    """Temporarily swap Tensor.value for traced values; restore after."""
    saved = {n: t.value for n, t in tensors.items()}
    try:
        for n, t in tensors.items():
            if n in values:
                t.value = values[n]
        yield
    finally:
        for n, t in tensors.items():
            t.value = saved[n]


def _tensorize(args):
    return [a if isinstance(a, Tensor) else Tensor(a, stop_gradient=True)
            for a in args]


def _devalue(out):
    if isinstance(out, Tensor):
        return out.value
    if isinstance(out, (list, tuple)):
        return type(out)(_devalue(o) for o in out)
    return out


def functionalize(layer: Layer):
    """layer → (apply_fn, params, buffers) where
    apply_fn(params, buffers, *arg_arrays) -> (outputs, new_buffers) is pure."""
    params = dict(layer.named_parameters())
    buffers = dict(layer.named_buffers())

    def apply_fn(param_vals, buffer_vals, *args):
        with _bind(params, param_vals), _bind(buffers, buffer_vals):
            with no_grad_guard():
                out = layer(*_tensorize(args))
            new_buffers = {n: b.value for n, b in buffers.items()}
        return _devalue(out), new_buffers

    return apply_fn, {n: p.value for n, p in params.items()}, \
        {n: b.value for n, b in buffers.items()}


class TracedLayer:
    """ref: dygraph/jit.py:TracedLayer — here a jitted functional closure."""

    def __init__(self, layer, apply_fn, params, buffers):
        self._layer = layer
        self._apply = jax.jit(apply_fn)
        self._params = params
        self._buffers = buffers

    @staticmethod
    def trace(layer, inputs):
        apply_fn, params, buffers = functionalize(layer)
        traced = TracedLayer(layer, apply_fn, params, buffers)
        out = traced(*inputs)
        return out, traced

    def __call__(self, *args):
        vals = [a.value if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        out, _ = self._apply(self._params, self._buffers, *vals)
        if isinstance(out, (list, tuple)):
            return type(out)(Tensor(o, stop_gradient=True) for o in out)
        return Tensor(out, stop_gradient=True)

    def save_inference_model(self, dirname, feed=None, fetch=None):
        from ..io import _save_jit_model
        _save_jit_model(dirname, self._layer, self._params, self._buffers)


class InputSpec:
    """Declared input signature for `to_static` (paddle.static.InputSpec
    parity). `shape` entries of None mean "any size" — the concrete size is
    taken from the first call (each distinct size compiles once)."""

    def __init__(self, shape, dtype='float32', name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name


class ProgramTranslator:
    """ref: dygraph_to_static/program_translator.py:ProgramTranslator —
    process-wide switch; `enable(False)` makes every StaticFunction fall back
    to plain eager execution (the reference's escape hatch)."""

    _instance = None
    enabled = True

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def enable(self, flag: bool):
        ProgramTranslator.enabled = bool(flag)

    _fn_cache = {}

    def get_output(self, fn, *args, **kwargs):
        if isinstance(fn, StaticFunction):
            sf = fn
        else:
            sf = ProgramTranslator._fn_cache.get(fn)
            if sf is None:
                sf = ProgramTranslator._fn_cache.setdefault(
                    fn, StaticFunction(fn))
        return sf(*args, **kwargs)


def _find_layers(fn, instance, args, kwargs):
    """Layers whose parameters the traced program must treat as inputs: the
    bound instance, Layer positional/kw args, Layers captured in the
    function's closure cells, and Layers reachable from the function's module
    globals (one container level deep). The reference discovers these via AST
    rewrite + the program cache; here object inspection suffices."""
    layers = []
    seen = set()

    def add(obj, depth=0):
        if isinstance(obj, Layer):
            if id(obj) not in seen:
                seen.add(id(obj))
                layers.append(obj)
        elif depth < 1:
            if isinstance(obj, (list, tuple)):
                for v in obj:
                    add(v, depth + 1)
            elif isinstance(obj, dict):
                for v in obj.values():
                    add(v, depth + 1)

    add(instance)
    for a in args:
        add(a)
    for a in kwargs.values():
        add(a)
    raw = getattr(fn, '__wrapped__', fn)
    for cell in (getattr(raw, '__closure__', None) or ()):
        try:
            add(cell.cell_contents)
        except ValueError:
            pass
    for v in getattr(raw, '__globals__', {}).values():
        add(v)
    return layers


def _is_array_like(x):
    return isinstance(x, (Tensor, np.ndarray, jnp.ndarray)) or (
        hasattr(x, 'shape') and hasattr(x, 'dtype'))


class StaticFunction:
    """Real dygraph→static translation (ref: dygraph_to_static/
    program_translator.py:StaticFunction). Instead of AST-rewriting Python to
    a fluid Program, the eager function is traced with jax tracers — the tape
    dispatches the same registered functionals either way — producing ONE
    fused XLA program per input signature, cached by (shapes, dtypes, static
    args, grad mode). Gradients flow: the whole compiled forward becomes a
    single tape node whose vjp is itself a cached jitted XLA program."""

    def __init__(self, fn, input_spec=None):
        functools.update_wrapper(self, fn)
        self._fn = fn
        self._input_spec = input_spec
        self._instance = None
        self._cache = {}
        # per-instance caches keyed by the instance object itself via
        # weakref: id() reuse after GC can't resurrect a stale entry whose
        # closure captures a dead instance's parameters, and entries die
        # with their instance instead of leaking
        self._instance_caches = weakref.WeakKeyDictionary()
        # shared mutable cell: bound copies made by __get__ must increment
        # the same counter the descriptor exposes
        self._stats = {'compiles': 0}
        self._is_declarative = True

    @property
    def _compile_count(self):
        return self._stats['compiles']

    def __get__(self, instance, owner):
        if instance is None:
            return self
        bound = StaticFunction.__new__(StaticFunction)
        bound.__dict__ = dict(self.__dict__)
        bound._instance = instance
        return bound

    # -- signature handling --------------------------------------------------
    def _split_args(self, args, kwargs):
        """→ (arr_vals, rebuild, sig). Array-likes become traced inputs;
        everything else (python scalars, strings, None, Layers) is static and
        partakes in the cache key."""
        spec = self._input_spec
        arr_vals, slots = [], []
        sig = []

        def classify(x, spec_i=None):
            if _is_array_like(x):
                v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
                if spec_i is not None and spec_i.dtype is not None:
                    from ..core.dtypes import (to_jax_dtype,
                                               check_int32_bounds)
                    if str(spec_i.dtype) == 'int64' and \
                            not hasattr(v, 'aval'):
                        check_int32_bounds(np.asarray(v), 'InputSpec')
                    v = v.astype(to_jax_dtype(spec_i.dtype))
                arr_vals.append(v)
                slots.append(None)
                sig.append(('arr', v.shape, str(v.dtype)))
            else:
                slots.append(x)
                sig.append(('static', repr(x)))

        for i, a in enumerate(args):
            s = spec[i] if spec is not None and i < len(spec) else None
            classify(a, s)
        kw_keys = sorted(kwargs)
        for k in kw_keys:
            sig.append(('kw', k))
            classify(kwargs[k])

        def rebuild(traced_vals):
            it = iter(traced_vals)
            vals = [next(it) if s is None else s for s in slots]
            pos = vals[:len(args)]
            kw = dict(zip(kw_keys, vals[len(args):]))
            return pos, kw

        return arr_vals, rebuild, tuple(sig)

    def _compile(self, layers, arr_vals, rebuild, grad_flag, args_need_grad):
        from ..core.random import default_generator
        from .tape import watch_tensors
        all_params, all_buffers = {}, {}
        for li, layer in enumerate(layers):
            for n, p in layer.named_parameters():
                all_params[f'{li}.{n}'] = p
            for n, b in layer.named_buffers():
                all_buffers[f'{li}.{n}'] = b
        fn = self._fn
        # hold the instance only weakly: cache entries live in a
        # WeakKeyDictionary keyed by the instance, so a strong capture here
        # would pin the key and the entry could never be collected
        inst_ref = (weakref.ref(self._instance)
                    if self._instance is not None else None)

        def make_run(params, buffers, pnames, bnames):
            def run(pvals, bvals, key, arr):
                pts = {n: params[n] for n in pnames}
                bts = {n: buffers[n] for n in bnames}
                with _bind(pts, dict(zip(pnames, pvals))), \
                        _bind(bts, dict(zip(bnames, bvals))), \
                        default_generator.bind_base(key), no_grad_guard():
                    pos, kw = rebuild(_tensorize_keep(arr))
                    if inst_ref is not None:
                        out = fn(inst_ref(), *pos, **kw)
                    else:
                        out = fn(*pos, **kw)
                    new_b = [buffers[n].value for n in bnames]
                flat, treedef = jax.tree_util.tree_flatten(_devalue(out))
                return flat, treedef, new_b
            return run

        # Discovery pass (abstract, no FLOPs): bind every candidate
        # param/buffer to protect it from tracer leaks, watch which tensors
        # the function actually reads, and capture the output structure.
        touched = []
        k0 = default_generator.base_key()
        run_all = make_run(all_params, all_buffers,
                           list(all_params), list(all_buffers))
        with watch_tensors(touched):
            jax.eval_shape(lambda p, b, k, a: run_all(p, b, k, a)[0],
                           [p.value for p in all_params.values()],
                           [b.value for b in all_buffers.values()],
                           k0, tuple(arr_vals))
        touched_ids = {id(t) for t in touched}
        params = {n: p for n, p in all_params.items() if id(p) in touched_ids}
        # keep every buffer of any layer the trace actually used (buffer
        # writes don't flow through dispatch, so reads alone can't prove
        # a buffer is untouched)
        used_layers = set()
        for li, layer in enumerate(layers):
            names = [n for n in list(all_params) + list(all_buffers)
                     if n.startswith(f'{li}.')]
            tensors = [all_params.get(n) or all_buffers.get(n) for n in names]
            if any(id(t) in touched_ids for t in tensors):
                used_layers.add(li)
        buffers = {n: b for n, b in all_buffers.items()
                   if int(n.split('.', 1)[0]) in used_layers}
        pnames = list(params)
        bnames = list(buffers)

        treedef_box = []
        run = make_run(params, buffers, pnames, bnames)

        def run_flat(pvals, bvals, key, arr):
            flat, treedef, new_b = run(pvals, bvals, key, arr)
            if not treedef_box:
                treedef_box.append(treedef)
            return tuple(flat), new_b

        shapes = jax.eval_shape(run_flat,
                                [params[n].value for n in pnames],
                                [buffers[n].value for n in bnames],
                                k0, tuple(arr_vals))
        n_out = len(shapes[0])
        treedef = treedef_box.pop()

        needs_grad = grad_flag and (
            args_need_grad or
            any(getattr(p, 'trainable', False) for p in params.values()))
        if not needs_grad:
            infer = jax.jit(run_flat)
            return ('infer', infer, pnames, bnames, treedef, n_out,
                    params, buffers)

        def fwd_fn(pvals, bvals, key, arr):
            def g(pv, a):
                flat, new_b = run_flat(pv, bvals, key, a)
                out = flat[0] if n_out == 1 else tuple(flat)
                return out, new_b
            out, vjp_fn, new_b = jax.vjp(g, pvals, tuple(arr), has_aux=True)
            flat = [out] if n_out == 1 else list(out)
            return flat, new_b, vjp_fn

        fwd = jax.jit(fwd_fn)
        bwd = jax.jit(lambda vf, ct: vf(ct))
        return ('grad', (fwd, bwd), pnames, bnames, treedef, n_out,
                params, buffers)

    # -- call ----------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        if not ProgramTranslator.enabled:
            if self._instance is not None:
                return self._fn(self._instance, *args, **kwargs)
            return self._fn(*args, **kwargs)
        from ..core.random import default_generator
        arr_vals, rebuild, sig = self._split_args(args, kwargs)

        ordered_args = list(args) + [kwargs[k] for k in sorted(kwargs)]
        grad_flag = _grad_enabled()
        arg_req = tuple(isinstance(a, Tensor) and not a.stop_gradient
                        for a in ordered_args)
        # The entry stores the param/buffer Tensor objects it bound at
        # compile time, so cache hits skip layer discovery entirely.
        # (Rebinding a module global to a NEW Layer instance mid-training is
        # not retraced — same staleness semantics as the reference's program
        # cache, which also keys on function identity + input spec.)
        if self._instance is None:
            cache = self._cache
        else:
            cache = self._instance_caches.get(self._instance)
            if cache is None:
                cache = self._instance_caches.setdefault(self._instance, {})
        key = (sig, grad_flag, arg_req)
        entry = cache.get(key)
        if entry is None:
            layers = _find_layers(self._fn, self._instance, args, kwargs)
            entry = self._compile(layers, arr_vals, rebuild, grad_flag,
                                  any(arg_req))
            cache[key] = entry
            self._stats['compiles'] += 1  # one trace+compile per signature
        mode, compiled, pnames, bnames, treedef, n_out, params, buffers = entry
        pvals = [params[n].value for n in pnames]
        bvals = [buffers[n].value for n in bnames]
        rng = default_generator.next_key()

        if mode == 'infer':
            flat, new_b = compiled(pvals, bvals, rng, tuple(arr_vals))
            for n, v in zip(bnames, new_b):
                buffers[n].value = v
            outs = [Tensor(v, stop_gradient=True) for v in flat]
            return jax.tree_util.tree_unflatten(treedef, outs)

        fwd, bwd = compiled
        flat, new_b, vjp_fn = fwd(pvals, bvals, rng, tuple(arr_vals))
        for n, v in zip(bnames, new_b):
            buffers[n].value = v

        param_tensors = [params[n] for n in pnames]

        from .tape import Node

        def node_vjp(ct):
            p_cts, a_cts = bwd(vjp_fn, ct)
            by_val = list(p_cts) + list(a_cts)
            # map cotangents back to node.inputs order (params then arr args)
            return by_val

        # Tensors corresponding to traced arr inputs, in arr order
        arr_tensors = [a if isinstance(a, Tensor)
                       else Tensor(a, stop_gradient=True)
                       for a in ordered_args if _is_array_like(a)]
        node_inputs = param_tensors + arr_tensors
        node = Node(node_vjp, node_inputs, n_out,
                    [(v.shape, v.dtype) for v in flat], 'to_static')
        outs = []
        for i, v in enumerate(flat):
            t = Tensor(v)
            t._node = node
            t._out_index = i
            outs.append(t)
        return jax.tree_util.tree_unflatten(treedef, outs)


def _tensorize_keep(vals):
    return [Tensor(v, stop_gradient=True) for v in vals]


def _grad_enabled():
    from . import tape
    return tape.grad_enabled()


def declarative(fn=None, input_spec=None):
    """@declarative / @to_static: trace the eager function into a cached
    jitted XLA program (see StaticFunction)."""
    if fn is None:
        return lambda f: StaticFunction(f, input_spec=input_spec)
    return StaticFunction(fn, input_spec=input_spec)


to_static = declarative


class TrainStep:
    """Fully-fused training step: forward + vjp + optimizer update in ONE
    jitted XLA program with donated state (the TPU analogue of the reference
    ParallelExecutor fast path). Use:

        step = TrainStep(model, loss_fn, optimizer)
        loss = step(x_batch, y_batch)          # numpy/jax arrays in

    With `async_fetch=True` the call returns a
    :class:`~paddle_tpu.core.fetch_handle.FetchHandle` instead of the raw
    loss array and keeps up to `num_inflight_steps` (default 2) dispatched
    steps outstanding — `float(handle)` / `np.asarray(handle)` is the sync
    point, so logging the loss every k steps stops serializing the loop.
    `PADDLE_TPU_ASYNC=0` forces the synchronous behavior regardless.

    async_fetch composes with donation asymmetrically: `donate=True` (the
    default) updates params in place, which makes dispatch N+1 wait for
    step N to finish producing the donated buffers — host-side batch prep
    still overlaps the running step, but the dispatch window is
    effectively 1 deep. Pass `donate=False` for a true K-deep window at
    the cost of the double-buffer transient (2× param HBM).
    """

    def __init__(self, layer: Layer, loss_fn, optimizer, data_sharding=None,
                 remat=False, donate=True, amp_dtype=None, accum_steps=1,
                 async_fetch=False, num_inflight_steps=None, supervisor=None):
        from ..core.compile_cache import setup_persistent_cache
        setup_persistent_cache()   # second process reuses the compiled step
        self._layer = layer
        self._params = dict(layer.named_parameters())
        self._buffers = dict(layer.named_buffers())
        self._opt = optimizer
        self._loss_fn = loss_fn
        self._remat = remat
        self._data_sharding = data_sharding
        # donate=True (default): params/buffers/optimizer slots are donated
        # into the jitted step (jax donate_argnums) so XLA writes the update
        # in place — live HBM stays 1× params instead of 2×. The pre-step
        # buffers are invalidated (deleted-buffer semantics, asserted by the
        # donation-safety tests); donate=False keeps them valid.
        self._donate = bool(donate)
        # amp_dtype (e.g. jnp.bfloat16): params stay fp32 master weights;
        # the forward sees a low-precision cast, grads/updates are fp32 —
        # param dtypes are stable across steps so the step compiles once.
        self._amp_dtype = amp_dtype
        # accum_steps > 1: gradient merge (ref GradientMergeOptimizer,
        # optimizer.py:3870 semantics) — grads accumulate across k calls,
        # the optimizer applies once on the k-step mean, inside the same
        # jitted program via lax.cond so the step still compiles once.
        self._accum_steps = int(accum_steps)
        self._acc = None
        self._jitted = None
        self._slots = None
        self._step = 0
        # async_fetch: non-blocking loss handles + a bounded K-in-flight
        # dispatch window (executor-style pipelining for the fused step;
        # the loss output buffer is never donated, so a pending handle is
        # inherently snapshot-safe here). PADDLE_TPU_ASYNC=0 pins sync; a
        # numeric PADDLE_TPU_ASYNC sets the default window depth.
        from ..core.fetch_handle import (InflightWindow,
                                         resolve_inflight_steps)
        if async_fetch:
            self._async_k = resolve_inflight_steps(
                default=int(num_inflight_steps) if num_inflight_steps else 2)
        else:
            self._async_k = 0
        self._window = InflightWindow() if self._async_k else None
        # supervisor (resilience/supervisor.py): every call's loss is judged
        # at this boundary — a skip verdict restores the pre-step snapshot
        # via set_state, a rollback verdict surfaces on supervisor
        # .last_verdict; escalations raise TrainingDiverged out of the call.
        self._supervisor = supervisor
        if supervisor is not None and supervisor._train_step is None:
            supervisor._train_step = self

    def _build(self):
        layer = self._layer
        params = self._params
        buffers = self._buffers
        loss_fn = self._loss_fn
        opt = self._opt
        slot_names = opt._slot_names
        hypers = opt._hypers()
        has_lr = opt._has_lr_input
        from ..ops.registry import get_op
        update_fn = get_op(opt._op_type).fn
        clip = opt._grad_clip
        base_reg = opt.regularization
        regs = {n: (getattr(p, 'regularizer', None) or base_reg)
                for n, p in params.items()}
        trainable = {n for n, p in params.items() if p.trainable}

        amp_dtype = self._amp_dtype

        def forward(pvals, bvals, batch):
            if amp_dtype is not None:
                # params cast to the compute dtype; fp32 FEEDS meet the
                # low-precision weights at conv/matmul, which harmonize the
                # activation onto the weight dtype (ops/nn_ops.py) — labels
                # and loss targets are never touched
                pvals = {n: (v.astype(amp_dtype)
                             if jnp.issubdtype(v.dtype, jnp.floating) else v)
                         for n, v in pvals.items()}
            with _bind(params, pvals), _bind(buffers, bvals):
                with no_grad_guard():
                    loss = loss_fn(layer, *_tensorize(batch))
                new_b = {n: b.value for n, b in buffers.items()}
            lv = loss.value if isinstance(loss, Tensor) else loss
            return jnp.sum(lv), new_b

        if self._remat:
            forward = jax.checkpoint(forward, static_argnums=())

        def apply_update(train_p, grads, slots, lr):
            for n in grads:
                if regs[n] is not None:
                    grads[n] = regs[n].apply(train_p[n], grads[n])
            if clip is not None:
                grads = clip.apply_tree(grads)
            new_tp = {}
            new_slots = {}
            for n in trainable:
                args = [train_p[n], grads[n]] + \
                    [slots[n][s] for s in slot_names]
                if has_lr:
                    args.append(lr)
                res = update_fn(*args, **hypers)
                res = res if isinstance(res, tuple) else (res,)
                # pin param/slot dtypes across steps: bf16 params meeting
                # fp32 hypers/slots would otherwise promote the update to
                # fp32, which breaks donated-buffer reuse (shape/dtype must
                # match the donated input) and, under accum_steps>1, the
                # lax.cond branch signatures
                new_tp[n] = res[0].astype(train_p[n].dtype)
                new_slots[n] = {
                    s: r.astype(slots[n][s].dtype)
                    for s, r in zip(slot_names, res[1:])}
            return new_tp, new_slots

        accum_steps = self._accum_steps
        if accum_steps <= 1:
            def step(pvals, bvals, slots, lr, batch):
                train_p = {n: pvals[n] for n in trainable}
                frozen_p = {n: v for n, v in pvals.items()
                            if n not in trainable}

                def f(tp):
                    return forward({**frozen_p, **tp}, bvals, batch)

                (loss, new_b), grads = \
                    jax.value_and_grad(f, has_aux=True)(train_p)
                new_tp, new_slots = apply_update(train_p, grads, slots, lr)
                return {**frozen_p, **new_tp}, new_b, new_slots, loss

            return jax.jit(step, donate_argnums=(0, 1, 2)
                           if self._donate else ())

        def step(pvals, bvals, slots, acc, count, lr, batch):
            # gradient merge: accumulate, and on every k-th call apply the
            # optimizer on the k-step MEAN (regularizer/clip act on the
            # merged grad, matching ref GradientMergeOptimizer which scales
            # by 1/k before the inner optimizer runs)
            train_p = {n: pvals[n] for n in trainable}
            frozen_p = {n: v for n, v in pvals.items() if n not in trainable}

            def f(tp):
                return forward({**frozen_p, **tp}, bvals, batch)

            (loss, new_b), grads = jax.value_and_grad(f, has_aux=True)(train_p)
            acc = jax.tree_util.tree_map(lambda a, g: a + g, acc, grads)
            do_apply = (count + 1) % accum_steps == 0

            def on_apply(operand):
                acc, slots = operand
                mean_g = {n: a / accum_steps for n, a in acc.items()}
                new_tp, new_slots = apply_update(dict(train_p), mean_g,
                                                 slots, lr)
                zero = jax.tree_util.tree_map(jnp.zeros_like, acc)
                return new_tp, new_slots, zero

            def on_skip(operand):
                acc, slots = operand
                return dict(train_p), slots, acc

            new_tp, new_slots, new_acc = jax.lax.cond(
                do_apply, on_apply, on_skip, (acc, slots))
            return ({**frozen_p, **new_tp}, new_b, new_slots, new_acc,
                    count + 1, loss)

        return jax.jit(step, donate_argnums=(0, 1, 2, 3)
                       if self._donate else ())

    def state(self):
        return ({n: p.value for n, p in self._params.items()},
                {n: b.value for n, b in self._buffers.items()})

    # -- checkpoint/resume (paddle_tpu/resilience/) --------------------
    def snapshot(self):
        """Non-blocking point-in-time capture for async checkpointing:
        → ({flat_key: FetchHandle}, meta). With donation on, the fused
        step donates its WHOLE state pytree every call — per-name
        protection is impossible — so each array is first cloned on-device
        (async dispatch, no host sync) and the handle wraps the clone; the
        checkpoint writer materializes D2H in the background while
        subsequent steps donate the originals freely."""
        from ..core.fetch_handle import FetchHandle

        def wrap(key, v):
            if self._donate and hasattr(v, 'block_until_ready'):
                v = jnp.copy(v)
            return FetchHandle(v, name=key)

        arrays = {}
        for n, p in self._params.items():
            arrays[f'param/{n}'] = wrap(f'param/{n}', p.value)
        for n, b in self._buffers.items():
            arrays[f'buffer/{n}'] = wrap(f'buffer/{n}', b.value)
        for n, slots in (self._slots or {}).items():
            for s, v in slots.items():
                arrays[f'slot/{s}/{n}'] = wrap(f'slot/{s}/{n}', v)
        if self._acc is not None:
            for n, v in self._acc.items():
                arrays[f'acc/{n}'] = wrap(f'acc/{n}', v)
            arrays['accum_count'] = wrap('accum_count', self._count)
        meta = {'step': self._step, 'accum_steps': self._accum_steps}
        lr = self._opt._learning_rate
        if hasattr(lr, 'step_num'):
            meta['lr_step_num'] = int(lr.step_num)
        return arrays, meta

    def set_state(self, arrays, meta=None):
        """Restore a :meth:`snapshot`. Call before or after the first step
        — restored optimizer slots/accumulators survive the lazy build.
        Unrecognized keys (e.g. ``scope/``-prefixed executor state in a
        combined capture) are ignored."""
        meta = dict(meta or {})
        slots = {}
        acc = {}
        for key, arr in arrays.items():
            if key.startswith('param/'):
                n = key[len('param/'):]
                if n in self._params:
                    self._params[n].value = jnp.asarray(arr)
            elif key.startswith('buffer/'):
                n = key[len('buffer/'):]
                if n in self._buffers:
                    self._buffers[n].value = jnp.asarray(arr)
            elif key.startswith('slot/'):
                _, s, n = key.split('/', 2)
                slots.setdefault(n, {})[s] = jnp.asarray(arr)
            elif key.startswith('acc/'):
                acc[key[len('acc/'):]] = jnp.asarray(arr)
            elif key == 'accum_count':
                self._count = jnp.asarray(arr, jnp.int32)
        if slots:
            self._slots = slots
        if acc:
            self._acc = acc
        if 'step' in meta:
            self._step = int(meta['step'])
        lr = self._opt._learning_rate
        if 'lr_step_num' in meta and hasattr(lr, 'step_num'):
            lr.step_num = meta['lr_step_num']

    def __call__(self, *batch):
        # hang watchdog lease over the fused dispatch (free when no process
        # watchdog is armed; see resilience/watchdog.py)
        lease = _watchdog.arm_step('train_step')
        try:
            if not _obs._ENABLED:
                loss = self._call_impl(batch)
            else:
                # span tree per fused step: build (first call only) +
                # execute nest under train_step/call; one steps.jsonl
                # record per call
                with _obs.span('train_step/call', step=self._step + 1):
                    loss = self._call_impl(batch)
                _obs.inc('train_step_calls',
                         help='fused TrainStep invocations')
                _obs.log_step(kind='train_step', step=self._step,
                              accum_steps=self._accum_steps,
                              donate=self._donate)
        finally:
            _watchdog.disarm(lease)
        if self._supervisor is not None:
            self._supervisor.end_of_step(self._step, loss)
        return loss

    def _call_impl(self, batch):
        if self._jitted is None:
            with _obs.span('train_step/build'):
                self._jitted = self._build()
        if self._slots is None:
            # skipped when set_state() restored checkpointed slots before
            # the first call — a resumed step must continue the restored
            # optimizer trajectory, not a fresh one
            self._slots = {
                n: {s: jnp.full(shp, fill, jnp.float32)
                    for s, (shp, fill) in
                    self._opt._slot_init(list(p.shape), p.dtype).items()}
                for n, p in self._params.items() if p.trainable}
        batch_vals = []
        for b in batch:
            arr = b.value if isinstance(b, Tensor) else jnp.asarray(b)
            if self._data_sharding is not None:
                arr = jax.device_put(arr, self._data_sharding)
            batch_vals.append(arr)
        pvals, bvals = self.state()
        if self._window is not None:
            # K-in-flight window: block on the oldest pending loss handle
            # only when the window is full, so this dispatch overlaps the
            # device still executing earlier steps
            self._window.admit(self._async_k)
        with _obs.span('train_step/execute'):
            if self._accum_steps > 1:
                if self._acc is None:
                    # accumulators carry the GRADIENT dtype (== param dtype;
                    # fp32 masters under amp): a hardcoded fp32 accumulator
                    # would promote `acc + grad` for bf16 params and the two
                    # lax.cond branches would disagree on dtypes (ADVICE r5)
                    self._acc = {n: jnp.zeros_like(p.value)
                                 for n, p in self._params.items()
                                 if p.trainable}
                    self._count = jnp.int32(0)
                new_p, new_b, self._slots, self._acc, self._count, loss = \
                    self._jitted(pvals, bvals, self._slots, self._acc,
                                 self._count,
                                 jnp.float32(self._opt._current_lr()),
                                 tuple(batch_vals))
            else:
                new_p, new_b, self._slots, loss = self._jitted(
                    pvals, bvals, self._slots,
                    jnp.float32(self._opt._current_lr()),
                    tuple(batch_vals))
        for n, p in self._params.items():
            p.value = new_p[n]
        for n, b in self._buffers.items():
            b.value = new_b[n]
        self._step += 1
        if hasattr(self._opt._learning_rate, 'step'):
            self._opt._learning_rate.step()
        if self._window is not None:
            from ..core.fetch_handle import FetchHandle
            from ..debugging import check_nan_inf_enabled
            handle = FetchHandle(loss, name='loss',
                                 check_nan=check_nan_inf_enabled())
            self._window.push([handle])
            return handle
        return loss
