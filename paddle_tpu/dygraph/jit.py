"""dygraph.jit: to_static / TracedLayer / fused training steps.

Parity with reference python/paddle/fluid/dygraph/jit.py +
dygraph_to_static/: where the reference translates Python AST to a static
Program, the TPU design traces the SAME eager code with jax tracers (the tape
runs the identical registered functionals), producing one fused XLA
computation. `TrainStep` additionally folds grad + optimizer update into that
single program — the production training path used by the benchmarks.
"""
from __future__ import annotations

import contextlib
import functools

import numpy as np
import jax
import jax.numpy as jnp

from .tape import Tensor, Parameter, no_grad_guard
from .layers import Layer


@contextlib.contextmanager
def _bind(tensors: dict, values: dict):
    """Temporarily swap Tensor.value for traced values; restore after."""
    saved = {n: t.value for n, t in tensors.items()}
    try:
        for n, t in tensors.items():
            if n in values:
                t.value = values[n]
        yield
    finally:
        for n, t in tensors.items():
            t.value = saved[n]


def _tensorize(args):
    return [a if isinstance(a, Tensor) else Tensor(a, stop_gradient=True)
            for a in args]


def _devalue(out):
    if isinstance(out, Tensor):
        return out.value
    if isinstance(out, (list, tuple)):
        return type(out)(_devalue(o) for o in out)
    return out


def functionalize(layer: Layer):
    """layer → (apply_fn, params, buffers) where
    apply_fn(params, buffers, *arg_arrays) -> (outputs, new_buffers) is pure."""
    params = dict(layer.named_parameters())
    buffers = dict(layer.named_buffers())

    def apply_fn(param_vals, buffer_vals, *args):
        with _bind(params, param_vals), _bind(buffers, buffer_vals):
            with no_grad_guard():
                out = layer(*_tensorize(args))
            new_buffers = {n: b.value for n, b in buffers.items()}
        return _devalue(out), new_buffers

    return apply_fn, {n: p.value for n, p in params.items()}, \
        {n: b.value for n, b in buffers.items()}


class TracedLayer:
    """ref: dygraph/jit.py:TracedLayer — here a jitted functional closure."""

    def __init__(self, layer, apply_fn, params, buffers):
        self._layer = layer
        self._apply = jax.jit(apply_fn)
        self._params = params
        self._buffers = buffers

    @staticmethod
    def trace(layer, inputs):
        apply_fn, params, buffers = functionalize(layer)
        traced = TracedLayer(layer, apply_fn, params, buffers)
        out = traced(*inputs)
        return out, traced

    def __call__(self, *args):
        vals = [a.value if isinstance(a, Tensor) else jnp.asarray(a)
                for a in args]
        out, _ = self._apply(self._params, self._buffers, *vals)
        if isinstance(out, (list, tuple)):
            return type(out)(Tensor(o, stop_gradient=True) for o in out)
        return Tensor(out, stop_gradient=True)

    def save_inference_model(self, dirname, feed=None, fetch=None):
        from ..io import _save_jit_model
        _save_jit_model(dirname, self._layer, self._params, self._buffers)


def declarative(fn):
    """@declarative / to_static: jit the eager function. Parameters of any
    Layer bound as `self` are captured fresh each call."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return fn(*args, **kwargs)
    wrapper._is_declarative = True
    return wrapper


to_static = declarative


class TrainStep:
    """Fully-fused training step: forward + vjp + optimizer update in ONE
    jitted XLA program with donated state (the TPU analogue of the reference
    ParallelExecutor fast path). Use:

        step = TrainStep(model, loss_fn, optimizer)
        loss = step(x_batch, y_batch)          # numpy/jax arrays in
    """

    def __init__(self, layer: Layer, loss_fn, optimizer, data_sharding=None,
                 remat=False, donate=True, amp_dtype=None):
        self._layer = layer
        self._params = dict(layer.named_parameters())
        self._buffers = dict(layer.named_buffers())
        self._opt = optimizer
        self._loss_fn = loss_fn
        self._remat = remat
        self._data_sharding = data_sharding
        # amp_dtype (e.g. jnp.bfloat16): params stay fp32 master weights;
        # the forward sees a low-precision cast, grads/updates are fp32 —
        # param dtypes are stable across steps so the step compiles once.
        self._amp_dtype = amp_dtype
        self._jitted = None
        self._slots = None
        self._step = 0

    def _build(self):
        layer = self._layer
        params = self._params
        buffers = self._buffers
        loss_fn = self._loss_fn
        opt = self._opt
        slot_names = opt._slot_names
        hypers = opt._hypers()
        has_lr = opt._has_lr_input
        from ..ops.registry import get_op
        update_fn = get_op(opt._op_type).fn
        clip = opt._grad_clip
        base_reg = opt.regularization
        regs = {n: (getattr(p, 'regularizer', None) or base_reg)
                for n, p in params.items()}
        trainable = {n for n, p in params.items() if p.trainable}

        amp_dtype = self._amp_dtype

        def forward(pvals, bvals, batch):
            if amp_dtype is not None:
                pvals = {n: (v.astype(amp_dtype)
                             if jnp.issubdtype(v.dtype, jnp.floating) else v)
                         for n, v in pvals.items()}
            with _bind(params, pvals), _bind(buffers, bvals):
                with no_grad_guard():
                    loss = loss_fn(layer, *_tensorize(batch))
                new_b = {n: b.value for n, b in buffers.items()}
            lv = loss.value if isinstance(loss, Tensor) else loss
            return jnp.sum(lv), new_b

        if self._remat:
            forward = jax.checkpoint(forward, static_argnums=())

        def step(pvals, bvals, slots, lr, batch):
            train_p = {n: pvals[n] for n in trainable}
            frozen_p = {n: v for n, v in pvals.items() if n not in trainable}

            def f(tp):
                return forward({**frozen_p, **tp}, bvals, batch)

            (loss, new_b), grads = jax.value_and_grad(f, has_aux=True)(train_p)
            for n in grads:
                if regs[n] is not None:
                    grads[n] = regs[n].apply(train_p[n], grads[n])
            if clip is not None:
                grads = clip.apply_tree(grads)
            new_p = dict(frozen_p)
            new_slots = {}
            for n in trainable:
                args = [train_p[n], grads[n]] + \
                    [slots[n][s] for s in slot_names]
                if has_lr:
                    args.append(lr)
                res = update_fn(*args, **hypers)
                res = res if isinstance(res, tuple) else (res,)
                new_p[n] = res[0]
                new_slots[n] = dict(zip(slot_names, res[1:]))
            return new_p, new_b, new_slots, loss

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def state(self):
        return ({n: p.value for n, p in self._params.items()},
                {n: b.value for n, b in self._buffers.items()})

    def __call__(self, *batch):
        if self._jitted is None:
            self._jitted = self._build()
            self._slots = {
                n: {s: jnp.full(shp, fill, jnp.float32)
                    for s, (shp, fill) in
                    self._opt._slot_init(list(p.shape), p.dtype).items()}
                for n, p in self._params.items() if p.trainable}
        batch_vals = []
        for b in batch:
            arr = b.value if isinstance(b, Tensor) else jnp.asarray(b)
            if self._data_sharding is not None:
                arr = jax.device_put(arr, self._data_sharding)
            batch_vals.append(arr)
        pvals, bvals = self.state()
        new_p, new_b, self._slots, loss = self._jitted(
            pvals, bvals, self._slots, jnp.float32(self._opt._current_lr()),
            tuple(batch_vals))
        for n, p in self._params.items():
            p.value = new_p[n]
        for n, b in self._buffers.items():
            b.value = new_b[n]
        self._step += 1
        if hasattr(self._opt._learning_rate, 'step'):
            self._opt._learning_rate.step()
        return loss
