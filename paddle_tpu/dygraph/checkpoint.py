"""save_dygraph / load_dygraph (ref: python/paddle/fluid/dygraph/checkpoint.py).

Format: numpy .npz per state dict (portable, no pickle of arrays), plus a
small JSON manifest. Large sharded states use io.orbax paths instead.
"""
from __future__ import annotations

import json
import os

import numpy as np

from .tape import Tensor


def save_dygraph(state_dict, model_path):
    """state_dict: name → Tensor/ndarray. Writes {model_path}.pdparams(.npz)."""
    os.makedirs(os.path.dirname(model_path) or '.', exist_ok=True)
    arrays = {}
    meta = {}
    for k, v in state_dict.items():
        arr = np.asarray(v.value) if isinstance(v, Tensor) else np.asarray(v)
        arrays[k] = arr
        meta[k] = {'shape': list(arr.shape), 'dtype': str(arr.dtype)}
    # atomic commit (temp + os.replace, io.py helpers): a kill mid-save
    # can't leave a torn .npz that a later load would crash on
    from ..io import _atomic_savez, _atomic_write_text
    _atomic_savez(model_path + '.pdparams.npz', arrays)
    _atomic_write_text(model_path + '.pdparams.json', json.dumps(meta))


def load_dygraph(model_path, keep_name_table=False):
    path = model_path + '.pdparams.npz'
    if not os.path.exists(path):
        raise ValueError(f"no checkpoint at {model_path}")
    data = np.load(path)
    state = {k: data[k] for k in data.files}
    opt_path = model_path + '.pdopt.npz'
    opt_state = None
    if os.path.exists(opt_path):
        od = np.load(opt_path)
        opt_state = {k: od[k] for k in od.files}
    return state, opt_state
