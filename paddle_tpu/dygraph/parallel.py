"""Dygraph DataParallel (ref: python/paddle/fluid/dygraph/parallel.py).

TPU redesign: the reference all-reduces gradients over NCCL after backward;
here data parallelism is expressed by sharding the batch over a
jax.sharding.Mesh axis — XLA inserts the AllReduce over ICI during the fused
step (see parallel/mesh.py). The eager API keeps ref semantics:
scale_loss / apply_collective_grads are identity when world size is 1.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .layers import Layer
from .tape import Tensor


class ParallelEnv:
    """ref: dygraph/parallel.py:Env — rank/world topology discovery from the
    jax runtime (slice metadata) instead of env vars."""

    def __init__(self):
        self._nranks = jax.process_count()
        self._local_rank = jax.process_index()

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def rank(self):
        return self._local_rank

    @property
    def world_size(self):
        return self._nranks

    @property
    def dev_id(self):
        return 0

    @property
    def current_endpoint(self):
        return f"process:{self._local_rank}"

    @property
    def trainer_endpoints(self):
        return [f"process:{i}" for i in range(self._nranks)]


Env = ParallelEnv


def prepare_context(strategy=None):
    return ParallelEnv()


class DataParallel(Layer):
    """Wraps a Layer for data-parallel training. With a mesh (see
    parallel.mesh.get_default_mesh) the fused TrainStep shards batches over
    the 'dp' axis; eagerly, grads are averaged across the mesh when one is
    active (single-host: identity, matching ref nranks==1 behavior)."""

    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy or ParallelEnv()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @property
    def _nranks(self):
        from ..parallel.mesh import get_default_mesh
        mesh = get_default_mesh()
        if mesh is not None and 'dp' in mesh.axis_names:
            return mesh.shape['dp']
        return 1

    def scale_loss(self, loss):
        n = self._nranks
        if n <= 1:
            return loss
        return loss * (1.0 / n)

    def apply_collective_grads(self):
        """Average gradients across the dp mesh axis. Under the sharded jit
        step XLA already psums grads; eager path averages explicitly."""
        n = self._nranks
        if n <= 1:
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                p.grad = p.grad / n

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix=''):
        return self._layers.named_parameters(prefix)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)

    load_dict = set_dict
