"""Dygraph DataParallel (ref: python/paddle/fluid/dygraph/parallel.py).

TPU redesign: the reference all-reduces gradients over NCCL after backward;
here data parallelism is expressed by sharding the batch over a
jax.sharding.Mesh axis — XLA inserts the AllReduce over ICI during the fused
step (see parallel/mesh.py). The eager API keeps ref semantics:
scale_loss / apply_collective_grads are identity when world size is 1.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .layers import Layer
from .tape import Tensor


class ParallelEnv:
    """ref: dygraph/parallel.py:Env — rank/world topology discovery from the
    jax runtime (slice metadata) instead of env vars."""

    def __init__(self):
        self._nranks = jax.process_count()
        self._local_rank = jax.process_index()

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def rank(self):
        return self._local_rank

    @property
    def world_size(self):
        return self._nranks

    @property
    def dev_id(self):
        return 0

    @property
    def current_endpoint(self):
        return f"process:{self._local_rank}"

    @property
    def trainer_endpoints(self):
        return [f"process:{i}" for i in range(self._nranks)]


Env = ParallelEnv


def prepare_context(strategy=None):
    return ParallelEnv()


_REDUCER = None


def _cross_process_reducer():
    """(shard_sharding, own_device, jitted_sum) over a 1-device-per-process
    mesh, built once: reuse keeps the jit cache warm (one compile per grad
    shape for the whole run), and picking each process's FIRST local device
    — grouped by process_index, never by raw device id order, which JAX
    does not guarantee to be process-contiguous — means every mesh row is
    owned by exactly the process whose grad shard it carries."""
    global _REDUCER
    if _REDUCER is None:
        import numpy as _np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        per_proc = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        devs = [per_proc[i] for i in sorted(per_proc)]
        mesh = Mesh(_np.array(devs), ('proc',))
        _REDUCER = (NamedSharding(mesh, P('proc')),
                    per_proc[jax.process_index()],
                    jax.jit(lambda g: jnp.sum(g, axis=0),
                            out_shardings=NamedSharding(mesh, P())))
    return _REDUCER


class DataParallel(Layer):
    """Wraps a Layer for data-parallel training (ref semantics: each rank
    computes a LOCAL loss; scale_loss divides by nranks before backward and
    apply_collective_grads all-reduce-sums grads after, so the net update
    uses the global-mean gradient).

    TPU redesign: a rank is a host process (single-controller SPMD — the
    devices under one process already compute the global gradient when the
    eager batch is the global batch or is sharded over the mesh 'dp' axis,
    because vjp sums over the whole batch). So both hooks are identity at
    process_count()==1 — dividing by the mesh dp size here would shrink
    grads by n² — and perform a REAL cross-process mean reduction under
    multi-host, replacing the reference's NCCL allreduce."""

    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy or ParallelEnv()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @property
    def _nranks(self):
        return jax.process_count()

    def scale_loss(self, loss):
        n = self._nranks
        if n <= 1:
            return loss
        return loss * (1.0 / n)

    def apply_collective_grads(self):
        """Sum gradients across host processes (each holds grads from its
        local batch). Single-process: grads are already the global sum —
        identity. Multi-host: a compiled XLA all-reduce (sum along a
        process-sharded axis), O(shape) per device — never materializes the
        (nranks, *shape) allgather the naive formulation would."""
        n = self._nranks
        if n <= 1:
            return
        shard_s, own_dev, reduce = _cross_process_reducer()
        for p in self._layers.parameters():
            if p.grad is None:
                continue
            local = jnp.asarray(p.grad)[None]  # this process's (1,*s) shard
            garr = jax.make_array_from_single_device_arrays(
                (n,) + tuple(local.shape[1:]), shard_s,
                [jax.device_put(local, own_dev)])
            p.grad = reduce(garr).addressable_data(0)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix=''):
        return self._layers.named_parameters(prefix)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)

    load_dict = set_dict
