"""Dygraph DataParallel (ref: python/paddle/fluid/dygraph/parallel.py).

TPU redesign: the reference all-reduces gradients over NCCL after backward;
here data parallelism is expressed by sharding the batch over a
jax.sharding.Mesh axis — XLA inserts the AllReduce over ICI during the fused
step (see parallel/mesh.py). The eager API keeps ref semantics:
scale_loss / apply_collective_grads are identity when world size is 1.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .layers import Layer
from .tape import Tensor


class ParallelEnv:
    """ref: dygraph/parallel.py:Env — rank/world topology discovery from the
    jax runtime (slice metadata) instead of env vars."""

    def __init__(self):
        self._nranks = jax.process_count()
        self._local_rank = jax.process_index()

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def rank(self):
        return self._local_rank

    @property
    def world_size(self):
        return self._nranks

    @property
    def dev_id(self):
        return 0

    @property
    def current_endpoint(self):
        return f"process:{self._local_rank}"

    @property
    def trainer_endpoints(self):
        return [f"process:{i}" for i in range(self._nranks)]


Env = ParallelEnv


def prepare_context(strategy=None):
    return ParallelEnv()


_REDUCER = None


def _cross_process_reducer():
    """(shard_sharding, own_device, reduce fns by comm dtype) over the
    partitioner's 1-device-per-process mesh (partition.process_mesh),
    built once: reuse keeps the jit cache warm (one compile per bundle
    shape for the whole run), and the mesh rows are process-owned by
    construction. The int8/bf16 reducers take the quantized payload
    rows (quant_collectives codec) and dequantize-sum in exact f32, so the
    bytes H2D'd and exchanged across processes are the compressed ones."""
    global _REDUCER
    if _REDUCER is None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel import quant_collectives as qc
        from ..partition import process_mesh
        mesh = process_mesh()
        own = {d.process_index: d for d in mesh.devices.ravel()}
        rep = NamedSharding(mesh, P())

        def dequant_sum(q, s):
            bs = qc.DEFAULT_BLOCK_SIZE
            part = (q.reshape(q.shape[0], -1, bs).astype(jnp.float32)
                    * s[:, :, None])
            return jnp.sum(part.reshape(q.shape[0], -1), axis=0)

        from ..core.compile_cache import setup_persistent_cache
        setup_persistent_cache()
        _REDUCER = (NamedSharding(mesh, P('proc')),
                    own[jax.process_index()],
                    {'f32': jax.jit(lambda g: jnp.sum(g, axis=0),
                                    out_shardings=rep),
                     'bf16': jax.jit(
                         lambda g: jnp.sum(g.astype(jnp.float32), axis=0),
                         out_shardings=rep),
                     'int8': jax.jit(dequant_sum, out_shardings=rep)})
    return _REDUCER


def _global_rows(local_row, shard_s, own_dev, n):
    """(1, *s) local value -> (n, *s) process-sharded global array."""
    return jax.make_array_from_single_device_arrays(
        (n,) + tuple(local_row.shape[1:]), shard_s,
        [jax.device_put(local_row, own_dev)])


_GATHER_FN = None


def _cross_process_gather(arr, n):
    """(R, …) local value -> (n·R, …) concatenation over the process
    mesh (replicated out-sharding over a process-sharded input = one
    XLA all-gather). Reuses the reducer's mesh/jit-cache discipline."""
    global _GATHER_FN
    shard_s, own_dev, _ = _cross_process_reducer()
    if _GATHER_FN is None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = NamedSharding(shard_s.mesh, P())
        _GATHER_FN = jax.jit(lambda g: g, out_shardings=rep)
    out = _GATHER_FN(_global_rows(arr[None], shard_s, own_dev, n))
    full = out.addressable_data(0)
    return full.reshape((-1,) + tuple(full.shape[2:]))


def _sparse_cross_process_push(grad, n, comm):
    """DP sync of one rows-only embedding gradient: all processes gather
    every peer's padded COO — int8 payloads cross with per-row f32
    scales — and the re-coalesce sums duplicate rows, which IS the
    gradient reduction. O(n·K·D) wire bytes vs the O(V·D) dense
    all-reduce the same table would otherwise pay (docs/SPARSE.md)."""
    from ..ops.sparse_ops import SparseRowsGrad
    from ..parallel import quant_collectives as qc
    rows = _cross_process_gather(jnp.asarray(grad.rows, jnp.int32), n)
    vals = jnp.asarray(grad.vals, jnp.float32)
    if comm == 'int8':
        q, s = qc.rowwise_quantize(vals)
        vals_all = qc.rowwise_dequantize(_cross_process_gather(q, n),
                                         _cross_process_gather(s, n))
    elif comm == 'bf16':
        vals_all = _cross_process_gather(
            vals.astype(jnp.bfloat16), n).astype(jnp.float32)
    else:
        vals_all = _cross_process_gather(vals, n)
    qc.record_sparse_collective('dygraph_dp_sparse', grad.nnz, grad.dim,
                                comm, n, grad.vocab * grad.dim)
    return SparseRowsGrad(rows, vals_all, grad.vocab,
                          grad.dim).coalesced()


def _cross_process_allreduce(flat, n, comm):
    """Sum one flat f32 bundle across `n` host processes; payload crosses
    the wire at `comm` dtype (quant_collectives codec), partials sum in
    exact f32. Returns the summed f32 bundle (on this process's device)."""
    from ..parallel import quant_collectives as qc
    shard_s, own_dev, fns = _cross_process_reducer()
    size = int(flat.shape[0])
    if comm == 'int8':
        q, s = qc.block_quantize(flat)
        red = fns['int8'](_global_rows(q[None], shard_s, own_dev, n),
                          _global_rows(s[None], shard_s, own_dev, n))
        return red.addressable_data(0)[:size]
    if comm == 'bf16':
        payload = flat.astype(jnp.bfloat16)
        return fns['bf16'](
            _global_rows(payload[None], shard_s, own_dev, n)
        ).addressable_data(0)
    return fns['f32'](
        _global_rows(flat[None], shard_s, own_dev, n)).addressable_data(0)


def _allreduce_bundles(params, reduce_flat, comm='f32', nranks=1,
                       record=True):
    """Flatten every pending gradient into ONE bundle per grad dtype,
    reduce each bundle with a single `reduce_flat(flat_f32) -> flat_f32`
    call, and scatter the results back into `p.grad` (the PR 3 fused-
    optimizer bundling trick applied to comms). Returns the number of
    reduce calls — one per dtype group, not one per parameter."""
    from ..ops.fused_ops import _bundle, _split
    from ..ops.sparse_ops import SparseRowsGrad
    from ..parallel import quant_collectives as qc
    groups = {}
    for p in params:
        if p.grad is None or isinstance(p.grad, SparseRowsGrad):
            continue    # sparse COO grads take the rows push, not a bundle
        groups.setdefault(jnp.asarray(p.grad).dtype, []).append(p)
    calls = 0
    for dtype, ps in sorted(groups.items(), key=lambda kv: str(kv[0])):
        flat, shapes, sizes = _bundle([p.grad for p in ps])
        reduced = reduce_flat(flat.astype(jnp.float32))
        calls += 1
        if record:
            qc.record_collective('dygraph_dp', int(flat.shape[0]), comm,
                                 nranks, phases=2)
            qc.record_quant_error('dygraph_dp', flat, comm)
        for p, g in zip(ps, _split(reduced.astype(dtype), shapes, sizes)):
            p.grad = g
    return calls


class DataParallel(Layer):
    """Wraps a Layer for data-parallel training (ref semantics: each rank
    computes a LOCAL loss; scale_loss divides by nranks before backward and
    apply_collective_grads all-reduce-sums grads after, so the net update
    uses the global-mean gradient).

    TPU redesign: a rank is a host process (single-controller SPMD — the
    devices under one process already compute the global gradient when the
    eager batch is the global batch or is sharded over the mesh 'dp' axis,
    because vjp sums over the whole batch). So both hooks are identity at
    process_count()==1 — dividing by the mesh dp size here would shrink
    grads by n² — and perform a REAL cross-process mean reduction under
    multi-host, replacing the reference's NCCL allreduce."""

    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy or ParallelEnv()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @property
    def _nranks(self):
        return jax.process_count()

    def scale_loss(self, loss):
        n = self._nranks
        if n <= 1:
            return loss
        return loss * (1.0 / n)

    def apply_collective_grads(self):
        """Sum gradients across host processes (each holds grads from its
        local batch). Single-process: grads are already the global sum —
        identity. Multi-host: ALL pending grads flatten into one bundle
        per dtype and each bundle is reduced with ONE compiled XLA
        all-reduce (sum along a process-sharded axis) instead of one
        dispatch per parameter — same bundling trick as the PR 3 fused
        optimizer. The bundle payload crosses processes at
        `DistributedStrategy.comm_dtype` / `PADDLE_TPU_COMM_DTYPE`
        (int8/bf16 block-quantized, partial sums exact f32 —
        parallel/quant_collectives.py; f32 = exact)."""
        n = self._nranks
        if n <= 1:
            return
        from ..parallel import quant_collectives as qc
        from ..ops.sparse_ops import SparseRowsGrad
        comm = qc.resolve_comm_dtype(
            getattr(self._strategy, 'comm_dtype', None))
        params = list(self._layers.parameters())
        _allreduce_bundles(
            params,
            lambda flat: _cross_process_allreduce(flat, n, comm),
            comm=comm, nranks=n)
        for p in params:
            if isinstance(p.grad, SparseRowsGrad):
                p.grad = _sparse_cross_process_push(p.grad, n, comm)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix=''):
        return self._layers.named_parameters(prefix)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)

    load_dict = set_dict
