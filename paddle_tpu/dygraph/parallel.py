"""Dygraph DataParallel (ref: python/paddle/fluid/dygraph/parallel.py).

TPU redesign: the reference all-reduces gradients over NCCL after backward;
here data parallelism is expressed by sharding the batch over a
jax.sharding.Mesh axis — XLA inserts the AllReduce over ICI during the fused
step (see parallel/mesh.py). The eager API keeps ref semantics:
scale_loss / apply_collective_grads are identity when world size is 1.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .layers import Layer
from .tape import Tensor


class ParallelEnv:
    """ref: dygraph/parallel.py:Env — rank/world topology discovery from the
    jax runtime (slice metadata) instead of env vars."""

    def __init__(self):
        self._nranks = jax.process_count()
        self._local_rank = jax.process_index()

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def rank(self):
        return self._local_rank

    @property
    def world_size(self):
        return self._nranks

    @property
    def dev_id(self):
        return 0

    @property
    def current_endpoint(self):
        return f"process:{self._local_rank}"

    @property
    def trainer_endpoints(self):
        return [f"process:{i}" for i in range(self._nranks)]


Env = ParallelEnv


def prepare_context(strategy=None):
    return ParallelEnv()


class DataParallel(Layer):
    """Wraps a Layer for data-parallel training (ref semantics: each rank
    computes a LOCAL loss; scale_loss divides by nranks before backward and
    apply_collective_grads all-reduce-sums grads after, so the net update
    uses the global-mean gradient).

    TPU redesign: a rank is a host process (single-controller SPMD — the
    devices under one process already compute the global gradient when the
    eager batch is the global batch or is sharded over the mesh 'dp' axis,
    because vjp sums over the whole batch). So both hooks are identity at
    process_count()==1 — dividing by the mesh dp size here would shrink
    grads by n² — and perform a REAL cross-process mean reduction under
    multi-host, replacing the reference's NCCL allreduce."""

    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy or ParallelEnv()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @property
    def _nranks(self):
        return jax.process_count()

    def scale_loss(self, loss):
        n = self._nranks
        if n <= 1:
            return loss
        return loss * (1.0 / n)

    def apply_collective_grads(self):
        """Sum gradients across host processes (each holds grads from its
        local batch). Single-process: grads are already the global sum —
        identity. Multi-host: psum over all processes' devices."""
        n = self._nranks
        if n <= 1:
            return
        from jax.experimental import multihost_utils
        for p in self._layers.parameters():
            if p.grad is not None:
                # global-sum across processes: allgather (nranks, *shape)
                # then sum — scale_loss already divided by nranks
                gathered = multihost_utils.process_allgather(
                    jnp.asarray(p.grad))
                p.grad = jnp.sum(gathered, axis=0)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix=''):
        return self._layers.named_parameters(prefix)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)

    load_dict = set_dict
