"""Sequential / LayerList / ParameterList (ref: python/paddle/fluid/dygraph/
container.py)."""
from __future__ import annotations

from .layers import Layer
from .tape import Parameter


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], (list, tuple)):
            layers = layers[0]
        for i, l in enumerate(layers):
            if isinstance(l, (list, tuple)):
                name, sub = l
                self.add_sublayer(str(name), sub)
            else:
                self.add_sublayer(str(i), l)

    def __getitem__(self, i):
        return list(self._sub_layers.values())[i]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return list(self._sub_layers.values())[i]
        return self._sub_layers[str(i if i >= 0 else len(self) + i)]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def insert(self, index, sublayer):
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, sublayers):
        for l in sublayers:
            self.append(l)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, i):
        return self._parameters[str(i if i >= 0 else len(self) + i)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self
