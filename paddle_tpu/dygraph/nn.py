"""dygraph.nn layers (ref: python/paddle/fluid/dygraph/nn.py: Conv2D, Conv3D,
Pool2D, Linear, BatchNorm, Embedding, GRUUnit, LayerNorm, NCE, PRelu,
BilinearTensorProduct, Conv2DTranspose, Conv3DTranspose, GroupNorm,
SpectralNorm, TreeConv)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.dtypes import convert_dtype
from ..initializer import ConstantInitializer, NormalInitializer, XavierInitializer
from .layers import Layer
from .tape import Tensor, dispatch_op


class Conv2D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype='float32',
                 data_format='NCHW'):
        super().__init__()
        fs = filter_size if isinstance(filter_size, (list, tuple)) \
            else (filter_size, filter_size)
        std = math.sqrt(2.0 / (fs[0] * fs[1] * num_channels))
        # NHWC keeps HWIO weights so the conv lowers with no layout
        # transposes (PERF.md §2: NHWC end-to-end is ~6% faster on v5e)
        wshape = ([num_filters, num_channels // groups, fs[0], fs[1]]
                  if data_format == 'NCHW'
                  else [fs[0], fs[1], num_channels // groups, num_filters])
        self.weight = self.create_parameter(
            wshape, param_attr, dtype,
            default_initializer=NormalInitializer(0.0, std))
        self.bias = self.create_parameter([num_filters], bias_attr, dtype,
                                          is_bias=True)
        self._attrs = dict(stride=stride, padding=padding, dilation=dilation,
                           groups=groups, data_format=data_format)
        self._bias_axis = 1 if data_format == 'NCHW' else -1
        self._act = act

    def forward(self, x):
        out = dispatch_op('conv2d', {'x': x, 'weight': self.weight},
                          self._attrs)
        if self.bias is not None:
            out = dispatch_op('elementwise_add',
                              {'x': out, 'y': self.bias},
                              {'axis': self._bias_axis})
        if self._act:
            out = dispatch_op(self._act, {'x': out}, {})
        return out


class Conv3D(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype='float32'):
        super().__init__()
        fs = filter_size if isinstance(filter_size, (list, tuple)) \
            else (filter_size,) * 3
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups, *fs], param_attr, dtype)
        self.bias = self.create_parameter([num_filters], bias_attr, dtype,
                                          is_bias=True)
        self._attrs = dict(stride=stride, padding=padding, dilation=dilation,
                           groups=groups)
        self._act = act

    def forward(self, x):
        out = dispatch_op('conv3d', {'x': x, 'weight': self.weight}, self._attrs)
        if self.bias is not None:
            out = dispatch_op('elementwise_add', {'x': out, 'y': self.bias},
                              {'axis': 1})
        if self._act:
            out = dispatch_op(self._act, {'x': out}, {})
        return out


class Conv2DTranspose(Layer):
    def __init__(self, num_channels, num_filters, filter_size, output_size=None,
                 padding=0, stride=1, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype='float32'):
        super().__init__()
        fs = filter_size if isinstance(filter_size, (list, tuple)) \
            else (filter_size, filter_size)
        self.weight = self.create_parameter(
            [num_channels, num_filters // groups, fs[0], fs[1]], param_attr,
            dtype)
        self.bias = self.create_parameter([num_filters], bias_attr, dtype,
                                          is_bias=True)
        self._attrs = dict(stride=stride, padding=padding, dilation=dilation,
                           groups=groups)
        self._act = act

    def forward(self, x):
        out = dispatch_op('conv2d_transpose',
                          {'x': x, 'weight': self.weight}, self._attrs)
        if self.bias is not None:
            out = dispatch_op('elementwise_add', {'x': out, 'y': self.bias},
                              {'axis': 1})
        if self._act:
            out = dispatch_op(self._act, {'x': out}, {})
        return out


class Conv3DTranspose(Layer):
    def __init__(self, num_channels, num_filters, filter_size, padding=0,
                 stride=1, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype='float32'):
        super().__init__()
        fs = filter_size if isinstance(filter_size, (list, tuple)) \
            else (filter_size,) * 3
        self.weight = self.create_parameter(
            [num_channels, num_filters // groups, *fs], param_attr, dtype)
        self.bias = self.create_parameter([num_filters], bias_attr, dtype,
                                          is_bias=True)
        self._attrs = dict(stride=stride, padding=padding, dilation=dilation,
                           groups=groups)
        self._act = act

    def forward(self, x):
        out = dispatch_op('conv3d_transpose', {'x': x, 'weight': self.weight},
                          self._attrs)
        if self.bias is not None:
            out = dispatch_op('elementwise_add', {'x': out, 'y': self.bias},
                              {'axis': 1})
        if self._act:
            out = dispatch_op(self._act, {'x': out}, {})
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type='max', pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, data_format='NCHW'):
        super().__init__()
        self._attrs = dict(pool_size=pool_size, pool_type=pool_type,
                           pool_stride=pool_stride, pool_padding=pool_padding,
                           global_pooling=global_pooling, ceil_mode=ceil_mode,
                           exclusive=exclusive, data_format=data_format)

    def forward(self, x):
        return dispatch_op('pool2d', {'x': x}, self._attrs)


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None,
                 act=None, dtype='float32'):
        super().__init__()
        self.weight = self.create_parameter([input_dim, output_dim],
                                            param_attr, dtype)
        self.bias = self.create_parameter([output_dim], bias_attr, dtype,
                                          is_bias=True)
        self._act = act

    def forward(self, x):
        out = dispatch_op('matmul', {'x': x, 'y': self.weight}, {})
        if self.bias is not None:
            out = dispatch_op('elementwise_add', {'x': out, 'y': self.bias},
                              {'axis': -1})
        if self._act:
            out = dispatch_op(self._act, {'x': out}, {})
        return out


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype='float32', data_layout='NCHW', in_place=False,
                 moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False,
                 sync_stats=False):
        super().__init__()
        self.weight = self.create_parameter(
            [num_channels], param_attr, dtype,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_channels], bias_attr, dtype,
                                          is_bias=True)
        self._mean = self.register_buffer(
            '_mean_buf', self.create_buffer([num_channels], dtype, 0.0))
        self._variance = self.register_buffer(
            '_variance_buf', self.create_buffer([num_channels], dtype, 1.0))
        self._attrs = dict(momentum=momentum, epsilon=epsilon,
                           data_layout=data_layout,
                           use_global_stats=use_global_stats,
                           sync_stats=sync_stats)

    def forward(self, x):
        y, new_mean, new_var = dispatch_op(
            'batch_norm',
            {'x': x, 'scale': self.weight, 'bias': self.bias,
             'mean': self._mean, 'variance': self._variance},
            dict(self._attrs, is_test=not self.training))
        if self.training:
            self._mean.value = new_mean.value
            self._variance.value = new_var.value
        return y


class Embedding(Layer):
    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype='float32'):
        super().__init__()
        self.weight = self.create_parameter(
            list(size), param_attr, dtype,
            default_initializer=XavierInitializer())
        pad = -1 if padding_idx is None else (
            padding_idx if padding_idx >= 0 else size[0] + padding_idx)
        # is_sparse is LIVE (was: accepted-and-dropped): the tape emits
        # rows-only COO gradients for this table (docs/SPARSE.md)
        self._attrs = dict(padding_idx=pad, is_sparse=is_sparse,
                           is_distributed=is_distributed)

    def forward(self, ids):
        return dispatch_op('lookup_table', {'w': self.weight, 'ids': ids},
                           self._attrs)


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True,
                 epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
                 dtype='float32'):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        n = math.prod(normalized_shape)
        self.weight = self.create_parameter(
            [n], param_attr, dtype,
            default_initializer=ConstantInitializer(1.0)) if scale else None
        self.bias = self.create_parameter([n], bias_attr, dtype,
                                          is_bias=True) if shift else None
        self._epsilon = epsilon
        self._ndims = len(normalized_shape)
        self._act = act

    def forward(self, x):
        begin = x.ndim - self._ndims
        out = dispatch_op('layer_norm',
                          {'x': x, 'scale': self.weight, 'bias': self.bias},
                          {'begin_norm_axis': begin, 'epsilon': self._epsilon})
        if self._act:
            out = dispatch_op(self._act, {'x': out}, {})
        return out


class GroupNorm(Layer):
    def __init__(self, channels, groups, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, data_layout='NCHW', dtype='float32'):
        super().__init__()
        self.weight = self.create_parameter(
            [channels], param_attr, dtype,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([channels], bias_attr, dtype,
                                          is_bias=True)
        self._attrs = dict(groups=groups, epsilon=epsilon,
                           data_layout=data_layout)
        self._act = act

    def forward(self, x):
        out = dispatch_op('group_norm',
                          {'x': x, 'scale': self.weight, 'bias': self.bias},
                          self._attrs)
        if self._act:
            out = dispatch_op(self._act, {'x': out}, {})
        return out


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype='float32'):
        super().__init__()
        self.weight = self.create_parameter(list(weight_shape), None, dtype)
        self._attrs = dict(dim=dim, power_iters=power_iters, eps=eps)

    def forward(self, weight=None):
        w = weight if weight is not None else self.weight
        return dispatch_op('spectral_norm', {'w': w}, self._attrs)


class PRelu(Layer):
    def __init__(self, mode, channel=None, input_shape=None, param_attr=None,
                 dtype='float32'):
        super().__init__()
        if mode == 'all':
            shape = [1]
        elif mode == 'channel':
            shape = [channel]
        else:
            shape = [math.prod(input_shape[1:])]
        self.weight = self.create_parameter(
            shape, param_attr, dtype,
            default_initializer=ConstantInitializer(0.25))
        self._mode = mode

    def forward(self, x):
        return dispatch_op('prelu', {'x': x, 'alpha': self.weight},
                           {'mode': self._mode})


class BilinearTensorProduct(Layer):
    def __init__(self, input1_dim, input2_dim, output_dim, name=None,
                 act=None, param_attr=None, bias_attr=None, dtype='float32'):
        super().__init__()
        self.weight = self.create_parameter(
            [output_dim, input1_dim, input2_dim], param_attr, dtype)
        self.bias = self.create_parameter([output_dim], bias_attr, dtype,
                                          is_bias=True)
        self._act = act

    def forward(self, x, y):
        out = dispatch_op('bilinear_tensor_product',
                          {'x': x, 'y': y, 'weight': self.weight,
                           'bias': self.bias}, {})
        if self._act:
            out = dispatch_op(self._act, {'x': out}, {})
        return out


class GRUUnit(Layer):
    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation='tanh', gate_activation='sigmoid',
                 origin_mode=False, dtype='float32'):
        super().__init__()
        d = size // 3
        self.weight = self.create_parameter([d, d * 3], param_attr, dtype)
        self.bias = self.create_parameter([1, d * 3], bias_attr, dtype,
                                          is_bias=True)
        self._d = d
        self._origin_mode = origin_mode
        self._act = activation
        self._gate_act = gate_activation

    def forward(self, inputs, hidden):
        h, rh, gate = dispatch_op(
            'gru_unit', {'x': inputs, 'hidden': hidden,
                         'weight': self.weight, 'bias': self.bias},
            {'origin_mode': self._origin_mode})
        return h, rh, gate


class NCE(Layer):
    def __init__(self, num_total_classes, dim, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=10,
                 sampler='uniform', custom_dist=None, seed=0,
                 is_sparse=False, dtype='float32'):
        super().__init__()
        self.weight = self.create_parameter([num_total_classes, dim],
                                            param_attr, dtype)
        self.bias = self.create_parameter([num_total_classes], bias_attr,
                                          dtype, is_bias=True)
        self._attrs = dict(num_total_classes=num_total_classes,
                           num_neg_samples=num_neg_samples)

    def forward(self, input, label, sample_weight=None):
        return dispatch_op('nce', {'x': input, 'label': label,
                                   'weight': self.weight, 'bias': self.bias},
                           self._attrs)


class TreeConv(Layer):
    def __init__(self, feature_size, output_size, num_filters=1,
                 max_depth=8, act='tanh', param_attr=None, bias_attr=None,
                 name=None, dtype='float32'):
        super().__init__()
        self.weight = self.create_parameter(
            [feature_size, 3, output_size, num_filters], param_attr, dtype)
        self.bias = self.create_parameter([num_filters, output_size],
                                          bias_attr, dtype, is_bias=True)
        self._max_depth = max_depth
        self._act = act

    def forward(self, nodes_vector, edge_set):
        out = dispatch_op('tree_conv',
                          {'nodes': nodes_vector, 'edges': edge_set,
                           'weight': self.weight},
                          {'max_depth': self._max_depth})
        if self._act:
            out = dispatch_op(self._act, {'x': out}, {})
        return out


class Dropout(Layer):
    def __init__(self, p=0.5, seed=None,
                 dropout_implementation='downgrade_in_infer',
                 is_test=False):
        super().__init__()
        self._p = p
        self._impl = dropout_implementation

    def forward(self, x):
        return dispatch_op('dropout', {'x': x},
                           {'dropout_prob': self._p,
                            'is_test': not self.training,
                            'dropout_implementation': self._impl})
