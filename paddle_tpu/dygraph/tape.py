"""Dygraph autograd: Tensor (VarBase) + tape of jax.vjp nodes.

Parity with the reference imperative engine
(/root/reference/paddle/fluid/imperative/tracer.cc + gradient accumulation in
imperative/layer.cc), redesigned for XLA: every eager op call runs the SAME
registered jax functional the static graph uses, capturing its vjp; backward()
walks the tape in reverse topological order. Under `jit.to_static` the tape
records through tracers, so the whole step can still fuse into one XLA program.

Hot path: repeated eager dispatches reuse jitted kernels from an LRU cache
(see _EagerKernelCache below; PERF.md §9) instead of re-tracing per call.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import OrderedDict
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from .. import observability as _obs
from ..core import unique_name
from ..core.dtypes import convert_dtype, to_jax_dtype
from ..core.random import default_generator
from ..ops.registry import get_op

# THREAD-LOCAL grad switch (default on). A process-global flag let a
# serving/decode worker thread's no_grad_guard() — every engine step
# runs under one — disable tape recording for EVERY thread: a training
# loop on the main thread would intermittently build tensors with no
# grad history while a scheduler thread was mid-step, and backward()
# raised. Per-thread state keeps each guard scoped to its own thread
# (regression: tests/dygraph/test_tape.py).
_grad_state = threading.local()
_tensor_watchers = []


def grad_enabled():
    """Whether op dispatch on THIS thread records grad history."""
    return getattr(_grad_state, 'enabled', True)


# ---------------------------------------------------------------------------
# Eager per-op jitted-kernel cache.
#
# The reference avoids Python dispatch overhead with ~1,500 LoC of C++ Tracer
# (imperative/tracer.cc); the TPU analogue is to make the SECOND eager call of
# an op signature free: each dispatch is keyed by (op_type, input avals, arg
# structure, attrs) and reuses a jitted kernel — one XLA executable for the
# forward (returning the vjp residuals as a Partial pytree) plus one for the
# backward — instead of re-tracing jax.vjp through the functional every call.
# LRU-bounded; PADDLE_TPU_EAGER_CACHE=0 is the escape hatch; statistics are
# exposed through profiler.eager_kernel_cache_stats().
# ---------------------------------------------------------------------------

class _Unhashable(Exception):
    pass


def _attr_sig(v):
    """Canonical hashable form of an op attr value, or raise _Unhashable
    (arrays, closures, initializer objects → bypass the cache). Scalars are
    tagged with their type: True and 1 hash equal in Python but may mean
    different things to an op body."""
    if isinstance(v, (str, bytes, int, float, bool, type(None))):
        return (type(v).__name__, v)
    if isinstance(v, (np.bool_, np.integer)):
        return ('int', int(v))
    if isinstance(v, np.floating):
        return ('float', float(v))
    if isinstance(v, (list, tuple)):
        return tuple(_attr_sig(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _attr_sig(x)) for k, x in v.items()))
    raise _Unhashable


_BLOCKED = object()   # negative-cache sentinel: this key cannot be jitted


class _EagerKernelCache:
    """LRU of per-op-signature jitted kernels for the dygraph hot path."""

    def __init__(self, maxsize=None):
        if maxsize is None:
            maxsize = int(os.environ.get('PADDLE_TPU_EAGER_CACHE_SIZE',
                                         '1024'))
        self.maxsize = max(int(maxsize), 1)
        self.enabled = os.environ.get('PADDLE_TPU_EAGER_CACHE', '1') != '0'
        self._entries = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.bypasses = 0     # unhashable attrs or untraceable op bodies

    def stats(self):
        return {'enabled': self.enabled, 'size': len(self._entries),
                'maxsize': self.maxsize, 'hits': self.hits,
                'misses': self.misses, 'evictions': self.evictions,
                'bypasses': self.bypasses}

    def clear(self):
        self._entries.clear()
        self.reset_stats()

    def reset_stats(self):
        """Zero the counters but KEEP the compiled kernels — a profiled
        re-run over a warm cache must report fresh hit/miss numbers without
        paying the recompiles that clear() would force."""
        self.hits = self.misses = self.evictions = self.bypasses = 0

    def get(self, key):
        e = self._entries.get(key)
        if e is not None and e is not _BLOCKED:
            self._entries.move_to_end(key)
            self.hits += 1
        return e

    def put(self, key, entry):
        self.misses += 1
        self._entries[key] = entry
        while len(self._entries) > self.maxsize:
            self._entries.popitem(last=False)
            self.evictions += 1

    def block(self, key):
        """This signature failed to trace under jit (e.g. value-dependent
        Python control flow in the op body) — never try again."""
        self._entries[key] = _BLOCKED
        self.bypasses += 1


kernel_cache = _EagerKernelCache()


def kernel_cache_stats():
    return kernel_cache.stats()


def _collect_kernel_cache_gauges():
    """At-export snapshot of the kernel-cache counters into the telemetry
    registry — the cache's own hot path stays untouched."""
    s = kernel_cache.stats()
    g = _obs.registry.gauge(
        'eager_kernel_cache',
        'dygraph per-op jitted-kernel cache state (stat label selects '
        'hits/misses/evictions/bypasses/size/maxsize/enabled)')
    for k in ('size', 'maxsize', 'hits', 'misses', 'evictions', 'bypasses'):
        g.labels(stat=k).set(s[k])
    g.labels(stat='enabled').set(1.0 if s['enabled'] else 0.0)


_obs.registry.register_collector(_collect_kernel_cache_gauges)


@contextlib.contextmanager
def watch_tensors(collector: list):
    """Record every Tensor that flows into an op while active (used by
    `to_static` to discover which Parameters/buffers a traced function
    actually reads, so only those become inputs of the compiled program)."""
    _tensor_watchers.append(collector)
    try:
        yield
    finally:
        _tensor_watchers.pop()


@contextlib.contextmanager
def no_grad_guard():
    old = grad_enabled()
    _grad_state.enabled = False
    try:
        yield
    finally:
        _grad_state.enabled = old


def no_grad(fn=None):
    if fn is None:
        return no_grad_guard()
    import functools

    @functools.wraps(fn)
    def wrapper(*a, **k):
        with no_grad_guard():
            return fn(*a, **k)
    return wrapper


class Node:
    __slots__ = ('vjp_fn', 'inputs', 'n_outputs', 'out_avals', 'op_type',
                 'call_fn')

    def __init__(self, vjp_fn, inputs, n_outputs, out_avals, op_type,
                 call_fn=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs          # list[Tensor] in vjp arg order
        self.n_outputs = n_outputs
        self.out_avals = out_avals    # [(shape, dtype)] per output
        self.op_type = op_type
        # pure primal replay `call_fn(*input_values) -> op result` — lets
        # grad(create_graph=True) rebuild the forward as a jax function and
        # differentiate it to any order (ref: imperative/partial_grad_engine)
        self.call_fn = call_fn


class Tensor:
    """VarBase parity: eager tensor with autograd metadata."""

    def __init__(self, value, name=None, stop_gradient=False,
                 persistable=False, dtype=None):
        if dtype is not None:
            value = jnp.asarray(value, to_jax_dtype(dtype))
        else:
            value = jnp.asarray(value)
        self.value = value
        self.name = name or unique_name.generate('tensor')
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.grad = None
        self._node: Optional[Node] = None
        self._out_index = 0

    # ---- info ----
    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return convert_dtype(self.value.dtype)

    @property
    def ndim(self):
        return self.value.ndim

    def numpy(self):
        return np.asarray(self.value)

    def __array__(self, dtype=None, copy=None):
        # without this, np.asarray falls back to the sequence protocol and
        # dispatches one traced slice op PER ELEMENT (minutes for a matrix)
        if copy is False:
            # device memory cannot be exposed as a writable host view
            raise ValueError(
                "converting a paddle_tpu Tensor to numpy always copies "
                "from device memory; np.asarray(t, copy=False) cannot "
                "return a view")
        a = np.asarray(self.value)
        if dtype is not None:
            a = a.astype(dtype)
        return np.array(a, copy=True) if copy else a

    def item(self):
        return self.value.item()

    def __len__(self):
        return self.value.shape[0]

    def __repr__(self):
        return f"Tensor(name={self.name}, shape={self.shape}, " \
               f"dtype={self.dtype}, stop_gradient={self.stop_gradient})\n" \
               f"{self.value}"

    # ---- autograd ----
    def backward(self, retain_graph=False, backward_strategy=None):
        run_backward(self, retain_graph=retain_graph)

    def gradient(self):
        if self.grad is None:
            return None
        from ..ops.sparse_ops import SparseRowsGrad
        if isinstance(self.grad, SparseRowsGrad):
            # API parity: user code reads a dense (V, D) gradient even
            # when the tape carried rows-only COO
            return np.asarray(self.grad.densify())
        return np.asarray(self.grad)

    def clear_gradient(self):
        self.grad = None

    def detach(self):
        t = Tensor(self.value, stop_gradient=True)
        return t

    def set_value(self, value):
        v = value.value if isinstance(value, Tensor) else jnp.asarray(value)
        self.value = v.astype(self.value.dtype)

    def astype(self, dtype):
        return dispatch_op('cast', {'x': self}, {'dtype': convert_dtype(dtype)})

    # math dunders are attached by monkey_patch_tensor() below


class Parameter(Tensor):
    def __init__(self, value, name=None, trainable=True, regularizer=None,
                 **kw):
        super().__init__(value, name=name, stop_gradient=not trainable,
                         persistable=True)
        self.trainable = trainable
        self.regularizer = regularizer
        self.optimize_attr = {'learning_rate': kw.get('learning_rate', 1.0)}


def to_tensor_value(x):
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def dispatch_op(op_type, inputs, attrs):
    """Run a registered op eagerly, recording the tape. `inputs` is
    slot → Tensor | [Tensor] | None, matching the op's positional slots.

    Telemetry shim: with PADDLE_TPU_TELEMETRY off this is one bool check +
    one extra call frame on top of the real dispatch (_dispatch_op_impl);
    with it on, each dispatch lands one sample in the per-op latency
    histogram, labeled by whether the kernel cache served it."""
    if not _obs._ENABLED:
        return _dispatch_op_impl(op_type, inputs, attrs)
    hits0 = kernel_cache.hits
    t0 = time.perf_counter()
    try:
        with _obs.tracer.span('tape/' + op_type):
            return _dispatch_op_impl(op_type, inputs, attrs)
    finally:
        _obs.record_op_dispatch(op_type, time.perf_counter() - t0,
                                cached=kernel_cache.hits > hits0)


def _dispatch_op_impl(op_type, inputs, attrs):
    if op_type == 'lookup_table' and attrs.get('is_sparse'):
        out = _try_sparse_lookup(inputs, attrs)
        if out is not None:
            return out
    opdef = get_op(op_type)
    flat_tensors = []   # tensors participating in vjp
    arg_spec = []       # per-slot: ('single', idx) | ('list', [idx]) | ('const', v)
    for slot in opdef.input_slots:
        v = inputs.get(slot)
        if v is None:
            arg_spec.append(('const', None))
        elif isinstance(v, (list, tuple)):
            idxs = []
            for item in v:
                t = item if isinstance(item, Tensor) else Tensor(item, stop_gradient=True)
                idxs.append(len(flat_tensors))
                flat_tensors.append(t)
            arg_spec.append(('list', idxs))
        else:
            t = v if isinstance(v, Tensor) else Tensor(v, stop_gradient=True)
            arg_spec.append(('single', len(flat_tensors)))
            flat_tensors.append(t)

    if _tensor_watchers:
        for w in _tensor_watchers:
            w.extend(flat_tensors)

    attrs = dict(attrs)
    rng = None
    if opdef.needs_rng:
        rng = attrs.pop('key', None)
        if rng is None:
            rng = default_generator.next_key()

    def call_with(vals, key):
        kw = attrs if key is None else dict(attrs, key=key)
        args = []
        for kind, ref in arg_spec:
            if kind == 'const':
                args.append(ref)
            elif kind == 'single':
                args.append(vals[ref])
            else:
                args.append([vals[i] for i in ref])
        return opdef.fn(*args, **kw)

    def call(*vals):
        return call_with(vals, rng)

    vals = [t.value for t in flat_tensors]
    needs_grad = grad_enabled() and any(
        not t.stop_gradient and jnp.issubdtype(t.value.dtype, jnp.inexact)
        for t in flat_tensors)

    if kernel_cache.enabled:
        out = _cached_dispatch(op_type, opdef, arg_spec, attrs, call_with,
                               call, vals, rng, needs_grad, flat_tensors)
        if out is not _BLOCKED:
            return out

    if not needs_grad:
        result = call(*vals)
        return _wrap_outputs(opdef, result, node=None)

    result, vjp_fn = jax.vjp(call, *vals)
    flat_res = _flatten_result(opdef, result)
    node = Node(vjp_fn, flat_tensors, len(flat_res),
                [(r.shape, r.dtype) for r in flat_res], op_type,
                call_fn=call)
    return _wrap_outputs(opdef, result, node)


def _try_sparse_lookup(inputs, attrs):
    """Rows-only gradient path of ``lookup_table(is_sparse=True)``
    (docs/SPARSE.md): the eager forward is the plain dense gather; the
    tape node's hand-written vjp emits a padded-COO
    :class:`~paddle_tpu.ops.sparse_ops.SparseRowsGrad` — coalesced at a
    bucket-ladder rung — instead of letting jax.vjp scatter-add a dense
    V×D table gradient. Returns None (→ the generic dense dispatch) when
    the path does not apply: knob off, no-grad mode, frozen table,
    or under a to_static trace (the static path owns sparse there)."""
    from ..ops import sparse_ops
    w, ids = inputs.get('w'), inputs.get('ids')
    if not (isinstance(w, Tensor) and not w.stop_gradient
            and grad_enabled() and not _tensor_watchers
            and jnp.issubdtype(w.value.dtype, jnp.inexact)
            and sparse_ops.sparse_grad_enabled()):
        return None
    ids_val = ids.value if isinstance(ids, Tensor) else jnp.asarray(ids)
    if isinstance(w.value, jax.core.Tracer) \
            or isinstance(ids_val, jax.core.Tracer):
        return None
    opdef = get_op('lookup_table')
    padding_idx = attrs.get('padding_idx', -1)
    kernel_attrs = {k: v for k, v in attrs.items()
                    if k in ('padding_idx', 'is_sparse', 'is_distributed')}
    out_val = opdef.fn(w.value, ids_val, **kernel_attrs)
    vocab, dim = int(w.value.shape[0]), int(w.value.shape[1])
    flat_ids = sparse_ops.flatten_ids(ids_val)
    nnz = int(flat_ids.shape[0])
    bucket = sparse_ops.nnz_bucket(nnz)

    def vjp_fn(ct):
        ct = jnp.asarray(ct).reshape(nnz, dim)
        vals = ct
        if padding_idx is not None and padding_idx >= 0:
            # padded positions were zeroed independent of w: no gradient
            vals = jnp.where((flat_ids == padding_idx)[:, None], 0.0, vals)
        rows, coalesced = sparse_ops.coalesce_rows(flat_ids, vals, vocab,
                                                   bucket=bucket)
        dedup = None
        try:
            dedup = int(np.unique(np.asarray(flat_ids)).shape[0])
        except Exception:
            pass
        sparse_ops.record_sparse_lookup(nnz, bucket, dedup_rows=dedup,
                                        table=w.name)
        return (sparse_ops.SparseRowsGrad(rows, coalesced, vocab, dim),)

    node = Node(vjp_fn, [w], 1, [(out_val.shape, out_val.dtype)],
                'lookup_table',
                call_fn=lambda wv: opdef.fn(wv, ids_val, **kernel_attrs))
    return _wrap_outputs(opdef, out_val, node)


def _cached_dispatch(op_type, opdef, arg_spec, attrs, call_with, call, vals,
                     rng, needs_grad, flat_tensors):
    """Dispatch through the per-op jitted-kernel cache. Returns the wrapped
    outputs, or the _BLOCKED sentinel when this op must take the plain
    (re-traced) path: unhashable attrs, or a body jit cannot stage out."""
    try:
        spec_sig = tuple((kind, len(ref)) if kind == 'list' else (kind,)
                         for kind, ref in arg_spec)
        aval_sig = tuple(
            (v.shape, str(v.dtype), bool(getattr(v, 'weak_type', False)))
            for v in vals)
        key = (op_type, needs_grad, spec_sig, aval_sig, _attr_sig(attrs))
    except _Unhashable:
        kernel_cache.bypasses += 1
        return _BLOCKED

    entry = kernel_cache.get(key)
    if entry is _BLOCKED:
        return _BLOCKED
    if entry is None:
        # every eager kernel compiles through the persistent cross-process
        # XLA cache, same as Executor steps (lint_codebase.py invariant)
        from ..core.compile_cache import setup_persistent_cache
        setup_persistent_cache()
        if needs_grad:
            # fwd returns (primal outs, vjp residuals as a Partial pytree);
            # bwd re-applies that Partial under jit, so a repeated backward
            # through the same op signature is also a cache hit
            fwd = jax.jit(lambda vs, k: jax.vjp(
                lambda *v: call_with(v, k), *vs))
            bwd = jax.jit(lambda vf, ct: vf(ct))
        else:
            fwd = jax.jit(call_with)
            bwd = None
        entry = (fwd, bwd)

    try:
        if needs_grad:
            result, vjp_partial = entry[0](tuple(vals), rng)
        else:
            result = entry[0](tuple(vals), rng)
    except Exception:
        # e.g. value-dependent Python branching in the op body: fall back to
        # the eager path (a genuine user error re-raises there with an
        # untraced stack) and never retry this signature
        kernel_cache.block(key)
        return _BLOCKED

    if key not in kernel_cache._entries:
        kernel_cache.put(key, entry)

    if not needs_grad:
        return _wrap_outputs(opdef, result, node=None)

    bwd = entry[1]
    flat_res = _flatten_result(opdef, result)
    node = Node(lambda ct: bwd(vjp_partial, ct), flat_tensors, len(flat_res),
                [(r.shape, r.dtype) for r in flat_res], op_type,
                call_fn=call)
    return _wrap_outputs(opdef, result, node)


def _flatten_result(opdef, result):
    if len(opdef.output_slots) == 1:
        return list(result) if isinstance(result, (list, tuple)) else [result]
    flat = []
    for r in result:
        flat.extend(r if isinstance(r, (list, tuple)) else [r])
    return flat


def _wrap_outputs(opdef, result, node):
    def mk(val, idx):
        t = Tensor(val, stop_gradient=(node is None))
        t._node = node
        t._out_index = idx
        return t

    if len(opdef.output_slots) == 1:
        if isinstance(result, (list, tuple)):
            return [mk(v, i) for i, v in enumerate(result)]
        return mk(result, 0)
    outs = []
    idx = 0
    for r in result:
        if isinstance(r, (list, tuple)):
            outs.append([mk(v, idx + j) for j, v in enumerate(r)])
            idx += len(r)
        else:
            outs.append(mk(r, idx))
            idx += 1
    return tuple(outs)


def run_backward(loss: Tensor, retain_graph=False):
    """Reverse-topological tape walk (ref: imperative/engine.cc).
    With retain_graph=False (default, ref parity) the walked nodes' vjp
    residuals are released afterwards; a second backward() through them
    raises instead of silently re-accumulating."""
    if loss._node is None:
        raise RuntimeError("backward() on a tensor with no grad history")
    topo = []
    seen = set()

    def dfs(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for t in node.inputs:
            if t._node is not None:
                dfs(t._node)
        topo.append(node)

    dfs(loss._node)
    if any(n.vjp_fn is None for n in topo):
        raise RuntimeError(
            "trying to run backward() through a graph that has already been "
            "freed; pass retain_graph=True to the first backward() if you "
            "need to backward through it again")

    cotangents = {}  # id(node) → [array or None per output]

    def seed_ct(node, idx, val):
        lst = cotangents.setdefault(id(node), [None] * node.n_outputs)
        lst[idx] = val if lst[idx] is None else lst[idx] + val

    seed_ct(loss._node, loss._out_index,
            jnp.ones(loss.shape, to_jax_dtype(loss.dtype)))

    for node in reversed(topo):
        cts = cotangents.pop(id(node), None)
        if cts is None:
            continue
        full = []
        for i, (shape, dtype) in enumerate(node.out_avals):
            if cts[i] is not None:
                full.append(cts[i])
            else:
                full.append(jnp.zeros(shape, dtype))
        # rebuild the vjp cotangent structure (mirror of the primal output)
        ct_struct = _rebuild_ct(node, full)
        in_cts = node.vjp_fn(ct_struct)
        for t, g in zip(node.inputs, in_cts):
            if t.stop_gradient or not jnp.issubdtype(t.value.dtype, jnp.inexact):
                continue
            if type(g).__name__ == 'float0' or (hasattr(g, 'dtype') and
                                                g.dtype == jax.dtypes.float0):
                continue
            if t._node is not None:
                seed_ct(t._node, t._out_index, g)
            else:
                t.grad = g if t.grad is None else t.grad + g
        # leaf accumulation also for tensors that have nodes but are params?
        # params are leaves (no node), handled above.
    # intermediate tensors keep no .grad (matches ref default)
    if not retain_graph:
        for n in topo:
            n.vjp_fn = None          # release residual buffers (ref parity)


def _rebuild_ct(node, flat):
    """Reshape flat cotangent list back into the op's output structure."""
    if node.op_type == 'grad':
        # a grad(create_graph=True) node wraps jax.vjp(grad_fn, ...) where
        # grad_fn always returns a TUPLE of cotangents (even for a single
        # input), so its vjp demands a tuple — never a bare array
        return tuple(flat)
    try:
        opdef = get_op(node.op_type)
    except KeyError:
        return flat[0] if node.n_outputs == 1 else tuple(flat)
    if len(opdef.output_slots) == 1:
        if node.n_outputs == 1:
            return flat[0]
        return flat  # variadic single-slot (e.g. split) → list
    return tuple(flat)


def _node_flat_result(node, result):
    try:
        opdef = get_op(node.op_type)
    except KeyError:
        return list(result) if isinstance(result, (list, tuple)) else [result]
    return _flatten_result(opdef, result)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """Partial gradients d(outputs)/d(inputs) (ref: imperative/
    partial_grad_engine.cc via fluid.dygraph.grad).

    create_graph=True returns Tensors that carry grad history, enabling
    double-backward: the recorded subgraph between `inputs` and `outputs` is
    replayed as a pure jax function (each tape Node keeps its primal
    `call_fn`) and differentiated with jax.vjp — the grads' own node holds
    the vjp of THAT gradient function, so any order composes.

    `retain_graph` is accepted for API parity but has no effect: this engine
    replays primals instead of consuming vjp residuals, so grad() never
    frees the tape (a later backward()/grad() through the same graph always
    works)."""
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    if not outputs or not inputs:
        raise ValueError("grad(): outputs and inputs must be non-empty")
    for o in outputs:
        if o._node is None:
            raise RuntimeError(f"grad(): output {o.name} has no grad history")

    # collect the ancestor subgraph, stopping at `inputs`
    input_pos = {id(t): i for i, t in enumerate(inputs)}
    topo, seen = [], set()

    def dfs(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for t in node.inputs:
            if id(t) not in input_pos and t._node is not None:
                dfs(t._node)
        topo.append(node)

    for o in outputs:
        dfs(o._node)
    for n in topo:
        if n.call_fn is None:
            raise RuntimeError(
                f"grad(): op '{n.op_type}' on the path has no replayable "
                f"primal (e.g. a to_static fused node); use backward() or "
                f"compute this gradient inside the traced function")

    node_order = {id(n): i for i, n in enumerate(topo)}

    # unused-input detection (ref: allow_unused in partial_grad_engine):
    # an input participates iff some node in the ancestor subgraph reads it
    used = set()
    for n in topo:
        for t in n.inputs:
            if id(t) in input_pos:
                used.add(id(t))
    used |= {id(o) for o in outputs if id(o) in input_pos}
    for i, t in enumerate(inputs):
        if id(t) not in used and not allow_unused:
            raise ValueError(
                f"grad(): input {i} ({t.name}) is not reachable from "
                f"outputs; set allow_unused=True to get None for it")

    nogv_ids = set()
    if no_grad_vars:
        ngv = [no_grad_vars] if isinstance(no_grad_vars, Tensor) \
            else list(no_grad_vars)
        nogv_ids = {id(t) for t in ngv}

    def replay(*in_vals):
        produced = {}

        def val(t):
            if id(t) in input_pos:
                v = in_vals[input_pos[id(t)]]
            elif t._node is not None and id(t._node) in node_order:
                v = produced[(id(t._node), t._out_index)]
            else:
                v = t.value
            if id(t) in nogv_ids:
                v = jax.lax.stop_gradient(v)
            return v

        for node in topo:
            res = node.call_fn(*[val(t) for t in node.inputs])
            for i, v in enumerate(_node_flat_result(node, res)):
                produced[(id(node), i)] = v
        return tuple(val(o) for o in outputs)

    in_vals = tuple(t.value for t in inputs)
    if grad_outputs is None:
        cts = tuple(jnp.ones(o.shape, to_jax_dtype(o.dtype)) for o in outputs)
    else:
        gos = [grad_outputs] if isinstance(grad_outputs, Tensor) \
            else list(grad_outputs)
        cts = tuple(g.value if isinstance(g, Tensor) else jnp.asarray(g)
                    for g in gos)

    def grad_fn(*iv):
        _, vjp_fn = jax.vjp(replay, *iv)
        return vjp_fn(cts)    # replay always returns a tuple

    if not create_graph:
        gvals = grad_fn(*in_vals)
        return [None if id(t) not in used and allow_unused
                else Tensor(g, stop_gradient=True)
                for t, g in zip(inputs, gvals)]

    gvals, vjp2 = jax.vjp(grad_fn, *in_vals)
    node = Node(vjp2, inputs, len(gvals),
                [(g.shape, g.dtype) for g in gvals], 'grad',
                call_fn=grad_fn)
    outs = []
    for i, g in enumerate(gvals):
        if id(inputs[i]) not in used and allow_unused:
            outs.append(None)
            continue
        t = Tensor(g)
        t._node = node
        t._out_index = i
        outs.append(t)
    return outs


def monkey_patch_tensor():
    T = Tensor

    def _coerce(other):
        return other if isinstance(other, Tensor) else Tensor(other, stop_gradient=True)

    def binop(op_type, reverse=False):
        def impl(self, other):
            other = _coerce(other)
            x, y = (other, self) if reverse else (self, other)
            return dispatch_op(op_type, {'x': x, 'y': y}, {})
        return impl

    T.__add__ = binop('elementwise_add')
    T.__radd__ = binop('elementwise_add', True)
    T.__sub__ = binop('elementwise_sub')
    T.__rsub__ = binop('elementwise_sub', True)
    T.__mul__ = binop('elementwise_mul')
    T.__rmul__ = binop('elementwise_mul', True)
    T.__truediv__ = binop('elementwise_div')
    T.__rtruediv__ = binop('elementwise_div', True)
    T.__pow__ = binop('elementwise_pow')
    T.__mod__ = binop('elementwise_mod')
    T.__floordiv__ = binop('elementwise_floordiv')
    T.__matmul__ = lambda self, other: dispatch_op(
        'matmul', {'x': self, 'y': _coerce(other)}, {})
    T.__neg__ = lambda self: dispatch_op('scale', {'x': self}, {'scale': -1.0})
    T.__eq__ = binop('equal')
    T.__ne__ = binop('not_equal')
    T.__lt__ = binop('less_than')
    T.__le__ = binop('less_equal')
    T.__gt__ = binop('greater_than')
    T.__ge__ = binop('greater_equal')
    T.__hash__ = lambda self: id(self)

    def _getitem(self, idx):
        if _tensor_watchers:
            for w in _tensor_watchers:
                w.append(self)
        if isinstance(idx, Tensor):
            idx = idx.value
        if (self.stop_gradient or not grad_enabled()
                or not jnp.issubdtype(self.value.dtype, jnp.inexact)):
            return Tensor(self.value[idx], stop_gradient=True)
        getter = lambda v: v[idx]  # noqa: E731
        out, vjp_fn = jax.vjp(getter, self.value)
        node = Node(vjp_fn, [self], 1, [(out.shape, out.dtype)],
                    '__getitem__', call_fn=getter)
        t = Tensor(out)
        t._node = node
        return t

    T.__getitem__ = _getitem


monkey_patch_tensor()
