"""Dygraph autograd: Tensor (VarBase) + tape of jax.vjp nodes.

Parity with the reference imperative engine
(/root/reference/paddle/fluid/imperative/tracer.cc + gradient accumulation in
imperative/layer.cc), redesigned for XLA: every eager op call runs the SAME
registered jax functional the static graph uses, capturing its vjp; backward()
walks the tape in reverse topological order. Under `jit.to_static` the tape
records through tracers, so the whole step can still fuse into one XLA program.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core import unique_name
from ..core.dtypes import convert_dtype, to_jax_dtype
from ..core.random import default_generator
from ..ops.registry import get_op

_grad_enabled = True
_tensor_watchers = []


@contextlib.contextmanager
def watch_tensors(collector: list):
    """Record every Tensor that flows into an op while active (used by
    `to_static` to discover which Parameters/buffers a traced function
    actually reads, so only those become inputs of the compiled program)."""
    _tensor_watchers.append(collector)
    try:
        yield
    finally:
        _tensor_watchers.pop()


@contextlib.contextmanager
def no_grad_guard():
    global _grad_enabled
    old = _grad_enabled
    _grad_enabled = False
    try:
        yield
    finally:
        _grad_enabled = old


def no_grad(fn=None):
    if fn is None:
        return no_grad_guard()
    import functools

    @functools.wraps(fn)
    def wrapper(*a, **k):
        with no_grad_guard():
            return fn(*a, **k)
    return wrapper


class Node:
    __slots__ = ('vjp_fn', 'inputs', 'n_outputs', 'out_avals', 'op_type')

    def __init__(self, vjp_fn, inputs, n_outputs, out_avals, op_type):
        self.vjp_fn = vjp_fn
        self.inputs = inputs          # list[Tensor] in vjp arg order
        self.n_outputs = n_outputs
        self.out_avals = out_avals    # [(shape, dtype)] per output
        self.op_type = op_type


class Tensor:
    """VarBase parity: eager tensor with autograd metadata."""

    def __init__(self, value, name=None, stop_gradient=False,
                 persistable=False, dtype=None):
        if dtype is not None:
            value = jnp.asarray(value, to_jax_dtype(dtype))
        else:
            value = jnp.asarray(value)
        self.value = value
        self.name = name or unique_name.generate('tensor')
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.grad = None
        self._node: Optional[Node] = None
        self._out_index = 0

    # ---- info ----
    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return convert_dtype(self.value.dtype)

    @property
    def ndim(self):
        return self.value.ndim

    def numpy(self):
        return np.asarray(self.value)

    def item(self):
        return self.value.item()

    def __len__(self):
        return self.value.shape[0]

    def __repr__(self):
        return f"Tensor(name={self.name}, shape={self.shape}, " \
               f"dtype={self.dtype}, stop_gradient={self.stop_gradient})\n" \
               f"{self.value}"

    # ---- autograd ----
    def backward(self, retain_graph=False, backward_strategy=None):
        run_backward(self)

    def gradient(self):
        return None if self.grad is None else np.asarray(self.grad)

    def clear_gradient(self):
        self.grad = None

    def detach(self):
        t = Tensor(self.value, stop_gradient=True)
        return t

    def set_value(self, value):
        v = value.value if isinstance(value, Tensor) else jnp.asarray(value)
        self.value = v.astype(self.value.dtype)

    def astype(self, dtype):
        return dispatch_op('cast', {'x': self}, {'dtype': convert_dtype(dtype)})

    # math dunders are attached by monkey_patch_tensor() below


class Parameter(Tensor):
    def __init__(self, value, name=None, trainable=True, regularizer=None,
                 **kw):
        super().__init__(value, name=name, stop_gradient=not trainable,
                         persistable=True)
        self.trainable = trainable
        self.regularizer = regularizer
        self.optimize_attr = {'learning_rate': kw.get('learning_rate', 1.0)}


def to_tensor_value(x):
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


def dispatch_op(op_type, inputs, attrs):
    """Run a registered op eagerly, recording the tape. `inputs` is
    slot → Tensor | [Tensor] | None, matching the op's positional slots."""
    opdef = get_op(op_type)
    flat_tensors = []   # tensors participating in vjp
    arg_spec = []       # per-slot: ('single', idx) | ('list', [idx]) | ('const', v)
    for slot in opdef.input_slots:
        v = inputs.get(slot)
        if v is None:
            arg_spec.append(('const', None))
        elif isinstance(v, (list, tuple)):
            idxs = []
            for item in v:
                t = item if isinstance(item, Tensor) else Tensor(item, stop_gradient=True)
                idxs.append(len(flat_tensors))
                flat_tensors.append(t)
            arg_spec.append(('list', idxs))
        else:
            t = v if isinstance(v, Tensor) else Tensor(v, stop_gradient=True)
            arg_spec.append(('single', len(flat_tensors)))
            flat_tensors.append(t)

    if _tensor_watchers:
        for w in _tensor_watchers:
            w.extend(flat_tensors)

    attrs = dict(attrs)
    if opdef.needs_rng and 'key' not in attrs:
        attrs['key'] = default_generator.next_key()

    def call(*vals):
        args = []
        for kind, ref in arg_spec:
            if kind == 'const':
                args.append(ref)
            elif kind == 'single':
                args.append(vals[ref])
            else:
                args.append([vals[i] for i in ref])
        return opdef.fn(*args, **attrs)

    vals = [t.value for t in flat_tensors]
    needs_grad = _grad_enabled and any(
        not t.stop_gradient and jnp.issubdtype(t.value.dtype, jnp.inexact)
        for t in flat_tensors)

    if not needs_grad:
        result = call(*vals)
        return _wrap_outputs(opdef, result, node=None)

    result, vjp_fn = jax.vjp(call, *vals)
    flat_res = _flatten_result(opdef, result)
    node = Node(vjp_fn, flat_tensors, len(flat_res),
                [(r.shape, r.dtype) for r in flat_res], op_type)
    return _wrap_outputs(opdef, result, node)


def _flatten_result(opdef, result):
    if len(opdef.output_slots) == 1:
        return list(result) if isinstance(result, (list, tuple)) else [result]
    flat = []
    for r in result:
        flat.extend(r if isinstance(r, (list, tuple)) else [r])
    return flat


def _wrap_outputs(opdef, result, node):
    def mk(val, idx):
        t = Tensor(val, stop_gradient=(node is None))
        t._node = node
        t._out_index = idx
        return t

    if len(opdef.output_slots) == 1:
        if isinstance(result, (list, tuple)):
            return [mk(v, i) for i, v in enumerate(result)]
        return mk(result, 0)
    outs = []
    idx = 0
    for r in result:
        if isinstance(r, (list, tuple)):
            outs.append([mk(v, idx + j) for j, v in enumerate(r)])
            idx += len(r)
        else:
            outs.append(mk(r, idx))
            idx += 1
    return tuple(outs)


def run_backward(loss: Tensor):
    """Reverse-topological tape walk (ref: imperative/engine.cc)."""
    if loss._node is None:
        raise RuntimeError("backward() on a tensor with no grad history")
    topo = []
    seen = set()

    def dfs(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for t in node.inputs:
            if t._node is not None:
                dfs(t._node)
        topo.append(node)

    dfs(loss._node)

    cotangents = {}  # id(node) → [array or None per output]

    def seed_ct(node, idx, val):
        lst = cotangents.setdefault(id(node), [None] * node.n_outputs)
        lst[idx] = val if lst[idx] is None else lst[idx] + val

    seed_ct(loss._node, loss._out_index,
            jnp.ones(loss.shape, to_jax_dtype(loss.dtype)))

    for node in reversed(topo):
        cts = cotangents.pop(id(node), None)
        if cts is None:
            continue
        full = []
        for i, (shape, dtype) in enumerate(node.out_avals):
            if cts[i] is not None:
                full.append(cts[i])
            else:
                full.append(jnp.zeros(shape, dtype))
        # rebuild the vjp cotangent structure (mirror of the primal output)
        ct_struct = _rebuild_ct(node, full)
        in_cts = node.vjp_fn(ct_struct)
        for t, g in zip(node.inputs, in_cts):
            if t.stop_gradient or not jnp.issubdtype(t.value.dtype, jnp.inexact):
                continue
            if type(g).__name__ == 'float0' or (hasattr(g, 'dtype') and
                                                g.dtype == jax.dtypes.float0):
                continue
            if t._node is not None:
                seed_ct(t._node, t._out_index, g)
            else:
                t.grad = g if t.grad is None else t.grad + g
        # leaf accumulation also for tensors that have nodes but are params?
        # params are leaves (no node), handled above.
    # intermediate tensors keep no .grad (matches ref default)


def _rebuild_ct(node, flat):
    """Reshape flat cotangent list back into the op's output structure."""
    try:
        opdef = get_op(node.op_type)
    except KeyError:
        return flat[0] if node.n_outputs == 1 else tuple(flat)
    if len(opdef.output_slots) == 1:
        if node.n_outputs == 1:
            return flat[0]
        return flat  # variadic single-slot (e.g. split) → list
    return tuple(flat)


def monkey_patch_tensor():
    T = Tensor

    def _coerce(other):
        return other if isinstance(other, Tensor) else Tensor(other, stop_gradient=True)

    def binop(op_type, reverse=False):
        def impl(self, other):
            other = _coerce(other)
            x, y = (other, self) if reverse else (self, other)
            return dispatch_op(op_type, {'x': x, 'y': y}, {})
        return impl

    T.__add__ = binop('elementwise_add')
    T.__radd__ = binop('elementwise_add', True)
    T.__sub__ = binop('elementwise_sub')
    T.__rsub__ = binop('elementwise_sub', True)
    T.__mul__ = binop('elementwise_mul')
    T.__rmul__ = binop('elementwise_mul', True)
    T.__truediv__ = binop('elementwise_div')
    T.__rtruediv__ = binop('elementwise_div', True)
    T.__pow__ = binop('elementwise_pow')
    T.__mod__ = binop('elementwise_mod')
    T.__floordiv__ = binop('elementwise_floordiv')
    T.__matmul__ = lambda self, other: dispatch_op(
        'matmul', {'x': self, 'y': _coerce(other)}, {})
    T.__neg__ = lambda self: dispatch_op('scale', {'x': self}, {'scale': -1.0})
    T.__eq__ = binop('equal')
    T.__ne__ = binop('not_equal')
    T.__lt__ = binop('less_than')
    T.__le__ = binop('less_equal')
    T.__gt__ = binop('greater_than')
    T.__ge__ = binop('greater_equal')
    T.__hash__ = lambda self: id(self)

    def _getitem(self, idx):
        if _tensor_watchers:
            for w in _tensor_watchers:
                w.append(self)
        if isinstance(idx, Tensor):
            idx = idx.value
        if (self.stop_gradient or not _grad_enabled
                or not jnp.issubdtype(self.value.dtype, jnp.inexact)):
            return Tensor(self.value[idx], stop_gradient=True)
        out, vjp_fn = jax.vjp(lambda v: v[idx], self.value)
        node = Node(vjp_fn, [self], 1, [(out.shape, out.dtype)], '__getitem__')
        t = Tensor(out)
        t._node = node
        return t

    T.__getitem__ = _getitem


monkey_patch_tensor()
