"""Whole-Program liveness walk → peak-HBM memory plan. Zero tracing.

The executor lowers a Program into one jitted step whose live HBM is
state + feeds + activations-held-for-backward + gradients; before this
module, that peak was discovered by OOM. ``plan_program`` re-derives it
in milliseconds from the VarInfo lattice (infer.py) and the cost model
(cost.py), mirroring the executor's actual lowering:

- **state** (persistables): resident for the whole step. Donated buffers
  (params/slots XLA updates in place — executor.py donation split) count
  1×; kept-but-written buffers (fetch-aliased, or ``donate=False``) run
  copy-in/copy-out and count 2×.
- **feeds**: live from step start to their last reader.
- **activations**: live from producer to last reader. With a backward
  marker, forward intermediates are *residuals*: ``jax.value_and_grad``
  holds them until the backward consumes them — without checkpoints,
  every forward output is stored into the backward; with checkpoints
  (``RecomputeOptimizer`` / the ``auto_remat`` pass), only each segment
  boundary's live-set is stored and the backward re-materializes one
  segment at a time (``executor._remat_segments`` semantics), so the
  activation term becomes Σ boundary-carried bytes + the largest single
  segment's internal bytes (the recompute transient).
- **gradients**: one buffer per diff target, live from the backward
  until the update ops consume them.
- **backward FLOPs**: 2× the forward's (the standard fwd:bwd ratio);
  checkpointing adds one extra forward pass of the checkpointed span.

``select_checkpoints`` is the auto-remat planner: candidate boundaries
are single-output forward ops; the greedy picks the boundary that
minimizes predicted peak (ties → the narrowest live-set waist) until the
budget fits. Recompute cost is one extra forward pass regardless of
boundary count, so selection is bytes-first by construction —
"cheap-to-recompute" falls out of narrow waists having low
FLOPs-per-byte-saved.

Dynamic dims: UNKNOWN dims substitute ``assume_dim`` unless
``feed_shapes`` pins the real feed signature (the executor's plan hook
passes the actual shapes, making the plan exact for static programs).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Set

from ..framework import BACKWARD_OP_TYPE
from . import infer
from .checks import _op_external_reads
from .cost import (OpCost, dtype_nbytes, has_cost_rule, info_nbytes,
                   op_flops)
from .infer import UNKNOWN, VarInfo, declared_info, infer_op, seed_env

__all__ = ['MemoryPlan', 'plan_program', 'select_checkpoints',
           'gradient_bytes', 'solve_decode_pool_blocks',
           'decode_pool_report']


class Resident:
    """One var's residency contribution at the plan's peak."""

    __slots__ = ('name', 'nbytes', 'kind')

    def __init__(self, name, nbytes, kind):
        self.name = name
        self.nbytes = int(nbytes)
        self.kind = kind

    def __repr__(self):
        return f'Resident({self.name!r}, {self.nbytes}, {self.kind!r})'


def _mib(b):
    return b / float(1 << 20)


class MemoryPlan:
    """The planner's output: peak HBM, residency breakdown, per-op costs,
    and the backward/remat byte model. All byte figures use runtime
    widths (cost.dtype_nbytes); ``accounted_bytes`` is the
    state+feed+fetch subset the executor's measured counterpart
    (``program_measured_hbm_bytes``) reports."""

    def __init__(self):
        self.peak_bytes = 0
        self.peak_index = 0            # op index (bwd marker = the phase)
        self.peak_phase = ''           # 'forward' | 'backward' | 'op'
        self.state_bytes = 0           # Σ persistable bytes (1× each)
        self.donated_bytes = 0
        self.kept_written_bytes = 0    # kept AND written → 2× transient
        self.donation_saved_bytes = 0
        self.feed_bytes = 0
        self.fetch_bytes = 0
        self.grad_bytes = 0
        self.activation_bytes = 0      # stored into the backward
        self.transient_bytes = 0       # largest remat segment's internals
        self.fwd_flops = 0
        self.total_flops = 0
        self.checkpoints: List[str] = []
        self.op_costs: List[tuple] = []     # (idx, op_type, OpCost, site)
        self.timeline: List[tuple] = []     # (idx, op_type, live_bytes)
        self.residents: List[Resident] = []  # live set at the peak
        self.uncosted_ops: List[str] = []   # op types without a cost rule
        self.n_ops = 0
        self.plan_seconds = 0.0

    @property
    def accounted_bytes(self):
        """state + feeds + fetches — the subset with a measured runtime
        counterpart (executor fetch/feed/state byte accounting)."""
        return self.state_bytes + self.feed_bytes + self.fetch_bytes

    def top_residents(self, n=10):
        return sorted(self.residents, key=lambda r: -r.nbytes)[:n]

    def top_op_costs(self, n=10):
        return sorted(self.op_costs, key=lambda t: -t[2].flops)[:n]

    def to_dict(self, top=10):
        return {
            'peak_hbm_bytes': self.peak_bytes,
            'peak_hbm_mib': round(_mib(self.peak_bytes), 3),
            'peak_phase': self.peak_phase,
            'accounted_bytes': self.accounted_bytes,
            'state_bytes': self.state_bytes,
            'donated_bytes': self.donated_bytes,
            'donation_saved_bytes': self.donation_saved_bytes,
            'feed_bytes': self.feed_bytes,
            'fetch_bytes': self.fetch_bytes,
            'grad_bytes': self.grad_bytes,
            'activation_bytes': self.activation_bytes,
            'transient_bytes': self.transient_bytes,
            'fwd_flops': self.fwd_flops,
            'total_flops': self.total_flops,
            'checkpoints': list(self.checkpoints),
            'n_ops': self.n_ops,
            'plan_seconds': round(self.plan_seconds, 6),
            'top_residents': [
                {'name': r.name, 'bytes': r.nbytes, 'kind': r.kind}
                for r in self.top_residents(top)],
            'top_op_costs': [
                {'index': i, 'op': t, 'flops': c.flops, 'bytes': c.bytes,
                 'site': s}
                for i, t, c, s in self.top_op_costs(top)],
            'uncosted_ops': sorted(set(self.uncosted_ops)),
        }

    def format_report(self, top=10, budget_bytes=None):
        """Human-readable report lines (plan_program.py / lint --plan)."""
        lines = ['# Memory plan', '']
        verdict = ''
        if budget_bytes:
            fits = self.peak_bytes <= budget_bytes
            verdict = (f"  [{'FITS' if fits else 'EXCEEDS'} budget "
                       f"{_mib(budget_bytes):.1f} MiB]")
        lines.append(f"predicted peak HBM:  {_mib(self.peak_bytes):.3f} MiB "
                     f"(at {self.peak_phase}){verdict}")
        lines.append(f"state:               {_mib(self.state_bytes):.3f} MiB "
                     f"({_mib(self.donated_bytes):.3f} donated in-place, "
                     f"{_mib(self.donation_saved_bytes):.3f} saved vs "
                     f"copy-in/copy-out)")
        lines.append(f"feeds / fetches:     {_mib(self.feed_bytes):.3f} / "
                     f"{_mib(self.fetch_bytes):.3f} MiB")
        if self.grad_bytes:
            lines.append(f"gradients:           "
                         f"{_mib(self.grad_bytes):.3f} MiB")
            lines.append(f"activations->bwd:    "
                         f"{_mib(self.activation_bytes):.3f} MiB stored"
                         + (f" + {_mib(self.transient_bytes):.3f} MiB "
                            f"recompute transient "
                            f"({len(self.checkpoints)} checkpoint(s))"
                            if self.checkpoints else ' (no remat)'))
        lines.append(f"forward FLOPs:       {self.fwd_flops:,} "
                     f"(total {self.total_flops:,})")
        lines.append('')
        lines.append(f'## Top residents at peak (of {len(self.residents)})')
        for r in self.top_residents(top):
            lines.append(f"  {_mib(r.nbytes):>10.3f} MiB  {r.kind:<10} "
                         f"{r.name}")
        lines.append('')
        lines.append(f'## Top ops by FLOPs (of {self.n_ops})')
        for i, t, c, site in self.top_op_costs(top):
            lines.append(f"  {c.flops:>14,} flops  {_mib(c.bytes):>9.3f} "
                         f"MiB  #{i:<4} {t}"
                         + (f"  ({site})" if site else ''))
        if self.uncosted_ops:
            lines.append('')
            lines.append(f"(bytes-only coverage — no cost rule: "
                         f"{', '.join(sorted(set(self.uncosted_ops)))})")
        return lines


# ---------------------------------------------------------------------------
# the walk
# ---------------------------------------------------------------------------

def _last_reads(program, ops, fetch_set):
    """var name → last op index that reads it (external reads incl.
    sub-blocks); fetched names read at the very end."""
    last: Dict[str, int] = {}
    for idx, op in enumerate(ops):
        for n in _op_external_reads(op, program):
            last[n] = idx
        # backward marker reads loss/params/checkpoints by name
        for attr in ('loss', 'params', 'checkpoints'):
            v = op.attrs.get(attr)
            names = [v] if isinstance(v, str) else \
                list(v) if isinstance(v, (list, tuple)) else []
            for n in names:
                if isinstance(n, str):
                    last[n] = idx
    for n in fetch_set:
        last[n] = len(ops)
    return last


def plan_program(program, fetch_names=(), feed_names=(), feed_shapes=None,
                 donate=True, assume_dim=1, checkpoints=None):
    """Build the :class:`MemoryPlan` for `program`'s global block.

    `feed_shapes` (name → concrete shape) pins dynamic dims to the real
    feed signature; remaining UNKNOWN dims substitute `assume_dim`.
    `checkpoints` overrides the backward marker's checkpoint list (the
    auto-remat selector evaluates candidate sets through this)."""
    t0 = time.perf_counter()
    plan = MemoryPlan()
    blk = program.global_block()
    ops = list(blk.ops)
    plan.n_ops = len(ops)
    fetch_set = set(fetch_names)
    persist = {v.name for v in program.list_vars() if v.persistable}
    data_vars = {v.name for v in program.list_vars() if v.is_data}
    feed_set = (set(feed_names) | data_vars) - persist

    # --- infer walk: concrete-as-possible VarInfos + per-op costs ---
    env = seed_env(program)
    if feed_shapes:
        for n, shp in feed_shapes.items():
            base = env.get(n) or (declared_info(blk.var(n))
                                  if blk.has_var(n) else VarInfo())
            env[n] = VarInfo(tuple(shp), base.dtype, base.lod_level)

    bwd_idx = next((i for i, op in enumerate(ops)
                    if op.type == BACKWARD_OP_TYPE), None)
    marker = ops[bwd_idx] if bwd_idx is not None else None

    var_bytes: Dict[str, int] = {}       # resolved at binding time

    def nbytes_of(name):
        if name in var_bytes:
            return var_bytes[name]
        info = env.get(name)
        if info is None and blk.has_var(name):
            info = declared_info(blk.var(name))
        b = info_nbytes(info, assume_dim)
        var_bytes[name] = b
        return b

    for idx, op in enumerate(ops):
        if op.type == BACKWARD_OP_TYPE:
            # grads mirror their params
            for p, g in zip(op.attrs.get('params', []),
                            op.outputs.get('Grads', [])):
                if blk.has_var(p):
                    pi = env.get(p) or declared_info(blk.var(p))
                    env[g] = VarInfo(pi.shape, pi.dtype)
            # sparse tables emit padded-COO pairs (docs/SPARSE.md); K is
            # the runtime bucket rung — UNKNOWN prices at assume_dim
            for p, r, v in zip(op.attrs.get('sparse_params', []),
                               op.outputs.get('SparseRows', []),
                               op.outputs.get('SparseVals', [])):
                pi = (env.get(p) or declared_info(blk.var(p))
                      if blk.has_var(p) else VarInfo())
                dim = (pi.shape[1] if pi.shape is not None
                       and len(pi.shape) == 2 else UNKNOWN)
                env[r] = VarInfo((UNKNOWN,), 'int32')
                env[v] = VarInfo((UNKNOWN, dim), pi.dtype)
            plan.op_costs.append((idx, op.type, OpCost(), None))
            continue
        try:
            result = infer_op(op, env, blk)
        except infer.InferError:
            result = None
        if result is None:
            for n in op.output_names():
                env[n] = (declared_info(blk.var(n)) if blk.has_var(n)
                          else VarInfo())
        else:
            from ..ops.registry import get_op, has_op
            slots = (get_op(op.type).output_slots if has_op(op.type)
                     else list(op.outputs))
            for slot in slots:
                names = op.outputs.get(slot, [])
                if not names:
                    continue
                res = result.get(slot)
                infos = (list(res) if isinstance(res, (list, tuple))
                         else [res] * len(names))
                for n, info in zip(names, infos):
                    env[n] = info if info is not None else VarInfo()
            # any output slot the rule didn't mention keeps its declaration
            for n in op.output_names():
                if n not in env:
                    env[n] = (declared_info(blk.var(n)) if blk.has_var(n)
                              else VarInfo())
        c = OpCost(op_flops(op, env, blk, assume_dim),
                   sum(nbytes_of(n) for n in op.input_names()),
                   sum(nbytes_of(n) for n in op.output_names()))
        if not has_cost_rule(op.type):
            plan.uncosted_ops.append(op.type)
        plan.op_costs.append((idx, op.type, c,
                              getattr(op, '_site', None)))

    # --- byte categories ---
    state_written = set()
    for op in ops:
        state_written |= set(op.output_names()) & persist
    plan.state_bytes = sum(nbytes_of(n) for n in sorted(persist))
    for n in sorted(persist):
        kept = (not donate) or n in fetch_set
        if kept and n in state_written:
            plan.kept_written_bytes += nbytes_of(n)
        elif n in state_written:
            plan.donated_bytes += nbytes_of(n)
    plan.donation_saved_bytes = plan.donated_bytes
    plan.feed_bytes = sum(nbytes_of(n) for n in sorted(feed_set))
    plan.fetch_bytes = sum(nbytes_of(n) for n in sorted(fetch_set))

    last = _last_reads(program, ops, fetch_set)

    # --- forward/backward activation model ---
    fwd_flops = sum(c.flops for i, _, c, _ in plan.op_costs
                    if bwd_idx is None or i < bwd_idx)
    plan.fwd_flops = fwd_flops
    plan.total_flops = sum(c.flops for _, _, c, _ in plan.op_costs)

    eff_checkpoints = list(checkpoints) if checkpoints is not None else \
        list((marker.attrs.get('checkpoints') or []) if marker else [])
    plan.checkpoints = eff_checkpoints

    base = (plan.state_bytes + plan.kept_written_bytes)

    if marker is not None:
        plan.total_flops += 2 * fwd_flops        # bwd ≈ 2× fwd
        if eff_checkpoints:
            plan.total_flops += fwd_flops        # remat = one extra fwd
        fwd_ops = ops[:bwd_idx]
        plan.grad_bytes = sum(nbytes_of(g)
                              for g in marker.outputs.get('Grads', []))
        produced_at = {}
        for i, op in enumerate(fwd_ops):
            for n in op.output_names():
                if n not in persist and n not in produced_at:
                    produced_at[n] = i
        out_bytes = [0] * len(fwd_ops)
        for n, i in produced_at.items():
            out_bytes[i] += nbytes_of(n)
        # carried[b]: bytes of fwd-produced vars live across boundary b
        # (produced < b, still read at >= b — incl. the backward tail)
        carried = [0] * (len(fwd_ops) + 1)
        for n, i in produced_at.items():
            end = min(last.get(n, i), len(fwd_ops))
            lo, hi = i + 1, end            # live across boundaries lo..hi
            if hi >= lo:
                carried[lo] += nbytes_of(n)
                if hi + 1 <= len(fwd_ops):
                    carried[hi + 1] -= nbytes_of(n)
        for b in range(1, len(fwd_ops) + 1):
            carried[b] += carried[b - 1]

        def bwd_terms(bounds):
            """(stored, transient) for sorted segment boundaries."""
            if not bounds:
                return sum(out_bytes), 0
            stored = sum(carried[b] for b in bounds)
            transient, prev = 0, 0
            for b in list(bounds) + [len(fwd_ops)]:
                transient = max(transient, sum(out_bytes[prev:b]))
                prev = b
            # the final segment's outputs feed the loss/backward directly
            return stored + carried[len(fwd_ops)], transient

        bounds = sorted({produced_at[c] + 1 for c in eff_checkpoints
                         if c in produced_at})
        stored, transient = bwd_terms(bounds)
        plan.activation_bytes = stored
        plan.transient_bytes = transient
        plan._bwd_model = (out_bytes, carried, produced_at, last)

    # --- timeline + peak (incremental: O(ops + vars), not O(ops²)) ---
    live: Set[str] = set()
    live_bytes = 0
    expired: Dict[int, List[str]] = {}
    feed_expire: Dict[int, List[str]] = {}
    feed_live_bytes = 0
    for n in feed_set:
        e = last.get(n, -1)
        if e >= 0:
            feed_live_bytes += nbytes_of(n)
            feed_expire.setdefault(e, []).append(n)
    peak, peak_idx, peak_live = base, 0, set()
    for idx, op in enumerate(ops):
        if marker is not None and idx == bwd_idx:
            # the backward phase: residuals + grads + recompute transient
            cur = (base + feed_live_bytes + plan.activation_bytes
                   + plan.transient_bytes + plan.grad_bytes)
            if cur > peak:
                peak, peak_idx, peak_live = cur, idx, None
            plan.timeline.append((idx, op.type, cur))
            # after the backward: grads live until their tail readers
            for g in marker.outputs.get('Grads', []):
                if g not in live:
                    live.add(g)
                    live_bytes += nbytes_of(g)
                    expired.setdefault(last.get(g, idx), []).append(g)
        else:
            for n in op.output_names():
                if n not in persist and n not in live:
                    live.add(n)
                    live_bytes += nbytes_of(n)
                    expired.setdefault(last.get(n, idx), []).append(n)
            cur = base + live_bytes + feed_live_bytes
            if cur > peak:
                peak, peak_idx, peak_live = cur, idx, set(live)
            plan.timeline.append((idx, op.type, cur))
        for n in expired.pop(idx, ()):
            if n in live:
                live.discard(n)
                live_bytes -= nbytes_of(n)
        for n in feed_expire.pop(idx, ()):
            feed_live_bytes -= nbytes_of(n)

    plan.peak_bytes = peak
    plan.peak_index = peak_idx
    if marker is not None and peak_idx == bwd_idx:
        plan.peak_phase = 'backward'
    else:
        plan.peak_phase = (f'op #{peak_idx} '
                           f'{ops[peak_idx].type}' if ops else 'empty')

    # --- residents at peak ---
    res = []
    for n in sorted(persist):
        kind = 'state-kept' if ((not donate) or n in fetch_set) \
            else 'state'
        res.append(Resident(n, nbytes_of(n), kind))
    for n in sorted(feed_set):
        if last.get(n, -1) >= peak_idx:
            res.append(Resident(n, nbytes_of(n), 'feed'))
    if marker is not None and peak_idx == bwd_idx:
        fwd_ops = ops[:bwd_idx]
        stored_names = _stored_names(plan, fwd_ops, persist)
        for n in sorted(stored_names):
            res.append(Resident(n, nbytes_of(n), 'activation'))
        for g in marker.outputs.get('Grads', []):
            res.append(Resident(g, nbytes_of(g), 'gradient'))
    elif peak_live:
        for n in sorted(peak_live):
            res.append(Resident(n, nbytes_of(n), 'activation'))
    plan.residents = [r for r in res if r.nbytes > 0]
    plan.plan_seconds = time.perf_counter() - t0
    return plan


def _stored_names(plan, fwd_ops, persist):
    """Names the backward holds as residuals under the plan's checkpoint
    set (for the residents report)."""
    produced = [n for op in fwd_ops for n in op.output_names()
                if n not in persist]
    if not plan.checkpoints:
        return set(produced)
    # stored = boundary-carried vars; approximate with vars live across
    # any boundary (exact bytes already computed in activation_bytes)
    _, _, produced_at, last = plan._bwd_model
    bounds = sorted({produced_at[c] + 1 for c in plan.checkpoints
                     if c in produced_at})
    stored = set()
    for n, i in produced_at.items():
        end = min(last.get(n, i), len(fwd_ops))
        if any(i + 1 <= b <= end for b in bounds) or end >= len(fwd_ops):
            stored.add(n)
    return stored


def gradient_bytes(program, assume_dim=1):
    """Σ bytes of the backward marker's gradient outputs (runtime widths)
    — what `PADDLE_TPU_ALLREDUCE_BUCKET_MB=auto` sizes buckets from.
    0 for inference programs."""
    blk = program.global_block()
    marker = next((op for op in blk.ops if op.type == BACKWARD_OP_TYPE),
                  None)
    if marker is None:
        return 0
    total = 0
    for p in marker.attrs.get('params', []):
        if blk.has_var(p):
            total += info_nbytes(declared_info(blk.var(p)), assume_dim)
    return total


# ---------------------------------------------------------------------------
# decode-pool sizing (PADDLE_TPU_DECODE_HBM_MB → KV blocks)
# ---------------------------------------------------------------------------

def _model_state_bytes(model):
    """Σ parameter bytes of a dygraph model (runtime widths — the same 1×
    resident-state term plan_program charges for persistables)."""
    total = 0
    for p in model.parameters():
        v = getattr(p, 'value', p)
        total += int(getattr(v, 'nbytes', 0))
    return total


def _decode_kv_geometry(model):
    """(n_layers, n_heads, head_dim) of the model's KV cache, from the
    causal_lm config contract (``model.cfg.{num_hidden_layers,
    num_attention_heads, hidden_size}``). Raises a ValueError naming what
    is missing — a budget solve over unknown geometry would silently size
    the pool wrong."""
    cfg = getattr(model, 'cfg', None)
    try:
        n_layers = int(cfg.num_hidden_layers)
        n_heads = int(cfg.num_attention_heads)
        head_dim = int(cfg.hidden_size) // n_heads
    except (TypeError, AttributeError):
        raise ValueError(
            'decode-pool budget solve needs model.cfg with '
            'num_hidden_layers / num_attention_heads / hidden_size '
            '(the models/causal_lm.py config contract); pass an explicit '
            'max_blocks / PADDLE_TPU_DECODE_MAX_BLOCKS for models '
            'without it')
    return n_layers, n_heads, head_dim


def decode_pool_block_bytes(model, block_size, kv_dtype='f32'):
    """HBM bytes ONE KV-cache block costs across every layer: K and V,
    ``n_heads × block_size`` rows per layer, each row priced by
    kv_cache.kv_row_bytes at the storage dtype (int8 rows carry their f32
    scale)."""
    from ..serving.decode.kv_cache import kv_row_bytes
    n_layers, n_heads, head_dim = _decode_kv_geometry(model)
    return (n_layers * 2 * n_heads * int(block_size)
            * kv_row_bytes(head_dim, kv_dtype))


def solve_decode_pool_blocks(model, hbm_mb, block_size, kv_dtype='f32',
                             min_blocks=2):
    """The ``PADDLE_TPU_DECODE_HBM_MB`` budget solve: blocks =
    (budget − model state) // per-block KV bytes, floored at
    ``min_blocks`` (the engine passes max_blocks_per_seq + 1 so an empty
    pool always covers one maximal request). Raises when the budget does
    not even cover the model's resident state — a silent floor there
    would hide that the budget is fiction."""
    budget = int(hbm_mb) << 20
    state = _model_state_bytes(model)
    block_bytes = decode_pool_block_bytes(model, block_size, kv_dtype)
    if budget <= state:
        raise ValueError(
            f'PADDLE_TPU_DECODE_HBM_MB={hbm_mb} ({budget} bytes) does not '
            f'cover the model state ({state} bytes); nothing left for the '
            f'KV pool')
    return max(int(min_blocks), (budget - state) // block_bytes)


def decode_pool_report(model, hbm_mb, block_size, kv_dtype='f32',
                       min_blocks=2):
    """The solve, itemized for tools/plan_program.py — every term of the
    closed form inspectable next to the resulting block count."""
    n_layers, n_heads, head_dim = _decode_kv_geometry(model)
    from ..serving.decode.kv_cache import kv_row_bytes
    state = _model_state_bytes(model)
    block_bytes = decode_pool_block_bytes(model, block_size, kv_dtype)
    blocks = solve_decode_pool_blocks(model, hbm_mb, block_size, kv_dtype,
                                      min_blocks)
    return {
        'budget_mb': int(hbm_mb),
        'kv_dtype': kv_dtype,
        'block_size': int(block_size),
        'model_state_bytes': state,
        'kv_layers': n_layers,
        'kv_heads': n_heads,
        'head_dim': head_dim,
        'row_bytes': kv_row_bytes(head_dim, kv_dtype),
        'block_bytes': block_bytes,
        'num_blocks': int(blocks),
        'pool_bytes': int(blocks) * block_bytes,
    }


# ---------------------------------------------------------------------------
# auto-remat checkpoint selection
# ---------------------------------------------------------------------------

def select_checkpoints(program, budget_bytes, fetch_names=(),
                       feed_names=(), feed_shapes=None, donate=True,
                       assume_dim=1, max_checkpoints=16):
    """Greedy checkpoint selection from the plan: returns
    ``(checkpoint_names, predicted_peak_bytes)``. Empty list when the
    program already fits, has no backward, or no boundary helps.

    Candidates are forward ops with exactly one non-persistable output
    that later ops read — the boundaries ``executor._remat_segments``
    can split at. Each greedy round evaluates every remaining boundary
    against the closed-form backward model (Σ carried + max segment
    internal) and adds the one minimizing predicted peak; ties prefer
    the narrowest live-set waist. Recompute cost is one extra forward
    pass total, independent of how many boundaries are chosen, so the
    selection is bytes-first — exactly the low-FLOPs-per-byte-saved
    policy documented in docs/ANALYSIS.md."""
    no_remat = plan_program(program, fetch_names=fetch_names,
                            feed_names=feed_names, feed_shapes=feed_shapes,
                            donate=donate, assume_dim=assume_dim,
                            checkpoints=[])
    if no_remat.grad_bytes == 0 or not hasattr(no_remat, '_bwd_model'):
        return [], no_remat.peak_bytes
    if no_remat.peak_bytes <= budget_bytes:
        return [], no_remat.peak_bytes

    out_bytes, carried, produced_at, last = no_remat._bwd_model
    n_fwd = len(out_bytes)
    blk = program.global_block()
    persist = {v.name for v in program.list_vars() if v.persistable}
    # boundary b → checkpoint var name (single output of op b-1)
    boundary_var = {}
    for i, op in enumerate(blk.ops[:n_fwd]):
        outs = [n for n in op.output_names() if n not in persist]
        if len(outs) != 1:
            continue
        n = outs[0]
        if last.get(n, i) > i:                 # somebody reads it later
            boundary_var[i + 1] = n

    base_non_act = no_remat.peak_bytes - no_remat.activation_bytes \
        - no_remat.transient_bytes

    def peak_for(bounds):
        if not bounds:
            return no_remat.peak_bytes
        stored = sum(carried[b] for b in bounds) + carried[n_fwd]
        transient, prev = 0, 0
        for b in sorted(bounds) + [n_fwd]:
            transient = max(transient, sum(out_bytes[prev:b]))
            prev = b
        return base_non_act + stored + transient

    chosen: List[int] = []
    cur_peak = no_remat.peak_bytes
    while cur_peak > budget_bytes and len(chosen) < max_checkpoints:
        best = None
        for b, name in boundary_var.items():
            if b in chosen:
                continue
            p = peak_for(chosen + [b])
            key = (p, carried[b])
            if best is None or key < best[0]:
                best = (key, b)
        if best is None or best[0][0] >= cur_peak:
            break                              # no boundary helps further
        chosen.append(best[1])
        cur_peak = best[0][0]

    if not chosen:
        return [], no_remat.peak_bytes
    names = [boundary_var[b] for b in sorted(chosen)]
    return names, cur_peak
