"""Static per-op shape/dtype inference over the Program IR — zero tracing.

The executor lowers a Program through jax, so shape errors normally
surface as XLA trace failures with no pointer back to the op that
caused them. This module re-derives every var's ``VarInfo(shape, dtype,
lod_level)`` from op semantics alone: an :func:`infer_rule` registry maps
op types to small pure functions mirroring the registered kernel's
shape/dtype arithmetic (ops/*.py), and :func:`infer_block` propagates
infos op-by-op through a block.

Lattice: a dim is either a concrete ``int`` or :data:`UNKNOWN` (dynamic
batch dims, declared ``-1`` dims). A whole shape may be ``None`` (rank
unknown), and a dtype may be ``None``. Every rule treats UNKNOWN as
"compatible with anything" — dynamic dims never poison the analysis and
never produce false mismatches; only provably-inconsistent programs
raise :class:`InferError`.

Rules cover every op type the tier-1 recipes emit (elementwise /
broadcast, matmul / conv, reductions, reshape / concat / split, norms,
losses, the ``fused_*`` ops and ``c_allreduce_*``). Ops without a rule
propagate their declared var infos and are reported as ``no-infer-rule``
info diagnostics by checks.py — unknown ops degrade coverage, never
correctness.

Adding a rule (docs/ANALYSIS.md has the walkthrough)::

    @infer_rule('my_op')
    def _my_op(ctx):
        x = ctx.input('x')                  # VarInfo of the first 'x' name
        return {'Out': VarInfo(x.shape, x.dtype)}
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

__all__ = ['UNKNOWN', 'VarInfo', 'InferError', 'infer_rule', 'has_rule',
           'all_rules', 'OpCtx', 'infer_op', 'seed_env', 'declared_info']


class _UnknownDim:
    """Singleton lattice value for a statically-unknown dimension."""
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return '?'

    def __reduce__(self):
        return (_UnknownDim, ())


UNKNOWN = _UnknownDim()


def known(dim) -> bool:
    return dim is not UNKNOWN and dim is not None


def dims_agree(a, b) -> bool:
    """Whether two dims can be equal (UNKNOWN agrees with anything)."""
    return not (known(a) and known(b)) or a == b


def merge_dim(a, b):
    return a if known(a) else b


class VarInfo:
    """Static facts about one var: shape (tuple of int/UNKNOWN, or None =
    rank unknown), canonical dtype name (or None), lod_level."""

    __slots__ = ('shape', 'dtype', 'lod_level')

    def __init__(self, shape=None, dtype=None, lod_level=0):
        if shape is not None:
            shape = tuple(UNKNOWN if (s is None or s is UNKNOWN
                                      or (isinstance(s, int) and s < 0))
                          else int(s) for s in shape)
        self.shape = shape
        self.dtype = dtype
        self.lod_level = lod_level

    @property
    def ndim(self):
        return None if self.shape is None else len(self.shape)

    def numel(self):
        """Element count, or None when any dim is unknown."""
        if self.shape is None or any(not known(s) for s in self.shape):
            return None
        return int(np.prod(self.shape, dtype=np.int64)) if self.shape else 1

    def with_dtype(self, dtype):
        return VarInfo(self.shape, dtype, self.lod_level)

    def display_shape(self):
        """Shape with UNKNOWN rendered as -1 (fluid display convention)."""
        if self.shape is None:
            return None
        return tuple(-1 if not known(s) else s for s in self.shape)

    def __repr__(self):
        return f'VarInfo(shape={self.shape}, dtype={self.dtype})'


def shapes_agree(a: VarInfo, b: VarInfo) -> bool:
    """Whether two infos' shapes can denote the same array."""
    if a.shape is None or b.shape is None:
        return True
    if len(a.shape) != len(b.shape):
        return False
    return all(dims_agree(x, y) for x, y in zip(a.shape, b.shape))


class InferError(Exception):
    """A rule proved the op inconsistent. `kind` picks the diagnostic
    code: 'shape-mismatch', 'dtype-mismatch', or 'bad-attr'."""

    def __init__(self, message, kind='shape-mismatch'):
        super().__init__(message)
        self.kind = kind


def declared_info(var) -> VarInfo:
    """VarInfo from a framework.Variable declaration."""
    return VarInfo(var.shape, var.dtype, getattr(var, 'lod_level', 0) or 0)


def seed_env(program) -> Dict[str, VarInfo]:
    """Initial env for global-block inference: every declared var whose
    value exists before any op runs — data (feed) vars and persistables
    (scope state) — mapped to its declared info."""
    env = {}
    for v in program.list_vars():
        if v.is_data or v.persistable:
            env[v.name] = declared_info(v)
    return env


# ---------------------------------------------------------------------------
# dtype lattice helpers
# ---------------------------------------------------------------------------

def promote(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """jnp-style promotion over canonical dtype names; None is absorbing."""
    if a is None or b is None:
        return None
    if a == b:
        return a
    import jax.numpy as jnp
    from ..core.dtypes import convert_dtype, _NAME_TO_DTYPE
    try:
        return convert_dtype(jnp.promote_types(_NAME_TO_DTYPE[a],
                                               _NAME_TO_DTYPE[b]))
    except Exception:
        return None


def is_float(dtype: Optional[str]) -> Optional[bool]:
    if dtype is None:
        return None
    from ..core.dtypes import FLOAT_DTYPES
    return dtype in FLOAT_DTYPES


# ---------------------------------------------------------------------------
# shape arithmetic
# ---------------------------------------------------------------------------

def broadcast_shapes(a, b, what='operands'):
    """Numpy-style broadcast under the UNKNOWN lattice. Raises InferError
    only when two KNOWN dims are unequal and neither is 1."""
    if a is None or b is None:
        return None
    out = []
    ra, rb = list(a)[::-1], list(b)[::-1]
    for i in range(max(len(ra), len(rb))):
        da = ra[i] if i < len(ra) else 1
        db = rb[i] if i < len(rb) else 1
        if known(da) and known(db):
            if da != db and da != 1 and db != 1:
                raise InferError(
                    f'{what} are not broadcast-compatible: '
                    f'{tuple(a)} vs {tuple(b)} (dim {da} vs {db})')
            out.append(max(da, db))
        elif known(da) and da != 1:
            out.append(da)
        elif known(db) and db != 1:
            out.append(db)
        else:
            out.append(UNKNOWN)
    return tuple(out[::-1])


def paddle_broadcast(x: VarInfo, y: VarInfo, axis=-1):
    """Mirror ops.math_ops._align_y: paddle elementwise aligns y at `axis`
    of x by appending trailing 1-dims, then broadcasts."""
    xs, ys = x.shape, y.shape
    if xs is None or ys is None:
        return None
    if len(ys) == 0 or xs == ys or len(ys) >= len(xs):
        return broadcast_shapes(xs, ys)
    ax = len(xs) - len(ys) if axis in (-1, None) else axis
    trailing = len(xs) - ax - len(ys)
    if trailing < 0:
        raise InferError(
            f'elementwise axis={axis} places y{tuple(ys)} past the end '
            f'of x{tuple(xs)}', kind='bad-attr')
    return broadcast_shapes(xs, ys + (1,) * trailing)


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------

_RULES: Dict[str, object] = {}


def infer_rule(*op_types):
    """Decorator: register one inference rule for the given op types. The
    rule receives an :class:`OpCtx` and returns {output_slot: VarInfo |
    [VarInfo]} (missing slots default to unknown)."""

    def deco(fn):
        for t in op_types:
            if t in _RULES:
                raise ValueError(f'infer rule for {t!r} registered twice')
            _RULES[t] = fn
        return fn

    return deco


def has_rule(op_type: str) -> bool:
    return op_type in _RULES


def all_rules():
    return dict(_RULES)


class OpCtx:
    """What a rule may consult about one op: input infos resolved through
    the flow env (falling back to var declarations) and the op's attrs."""

    def __init__(self, op, env: Dict[str, VarInfo], block):
        self.op = op
        self.env = env
        self.block = block

    def info_of(self, name: str) -> VarInfo:
        if name in self.env:
            return self.env[name]
        if self.block is not None and self.block.has_var(name):
            return declared_info(self.block.var(name))
        return VarInfo()

    def inputs(self, slot: str) -> List[VarInfo]:
        return [self.info_of(n) for n in self.op.inputs.get(slot, [])]

    def input(self, slot: str) -> Optional[VarInfo]:
        names = self.op.inputs.get(slot, [])
        return self.info_of(names[0]) if names else None

    def require(self, slot: str) -> VarInfo:
        v = self.input(slot)
        if v is None:
            raise InferError(f'required input slot {slot!r} is empty',
                             kind='bad-attr')
        return v

    def attr(self, name, default=None):
        return self.op.attrs.get(name, default)

    def require_attr(self, name):
        if name not in self.op.attrs:
            raise InferError(f'required attr {name!r} is missing',
                             kind='bad-attr')
        return self.op.attrs[name]


def infer_op(op, env: Dict[str, VarInfo], block) -> Optional[Dict]:
    """Run the rule for `op`. Returns {slot: VarInfo|[VarInfo]} or None
    when no rule is registered. Raises InferError on proven
    inconsistency."""
    rule = _RULES.get(op.type)
    if rule is None:
        return None
    return rule(OpCtx(op, env, block))


# ---------------------------------------------------------------------------
# rules: elementwise / unary / comparisons
# ---------------------------------------------------------------------------

_ELTWISE_BINARY = ('elementwise_add', 'elementwise_sub', 'elementwise_mul',
                   'elementwise_div', 'elementwise_max', 'elementwise_min',
                   'elementwise_pow', 'elementwise_mod',
                   'elementwise_floordiv')


@infer_rule(*_ELTWISE_BINARY)
def _eltwise(ctx):
    x, y = ctx.require('x'), ctx.require('y')
    shape = paddle_broadcast(x, y, ctx.attr('axis', -1))
    return {'Out': VarInfo(shape, promote(x.dtype, y.dtype))}


@infer_rule('fused_elemwise_add_activation')
def _fused_add_act(ctx):
    functor = ctx.attr('functor', 'relu')
    if functor not in ('relu', 'sigmoid', 'tanh'):
        raise InferError(f'unknown functor {functor!r} for '
                         f'fused_elemwise_add_activation', kind='bad-attr')
    x, y = ctx.require('x'), ctx.require('y')
    shape = paddle_broadcast(x, y, ctx.attr('axis', -1))
    return {'Out': VarInfo(shape, promote(x.dtype, y.dtype))}


_SAME_SHAPE_UNARY = (
    'relu', 'sigmoid', 'tanh', 'exp', 'sqrt', 'rsqrt', 'abs', 'ceil',
    'floor', 'cos', 'sin', 'acos', 'asin', 'cosh', 'sinh', 'round',
    'reciprocal', 'log', 'square', 'softplus', 'softsign', 'sign', 'erf',
    'logsigmoid', 'atan', 'tanh_shrink', 'gelu', 'leaky_relu', 'relu6',
    'elu', 'selu', 'brelu', 'soft_relu', 'stanh', 'hard_sigmoid',
    'hard_swish', 'swish', 'hard_shrink', 'softshrink', 'thresholded_relu',
    'scale', 'clip', 'clip_by_norm', 'increment', 'assign',
    'fill_zeros_like', 'pow', 'l2_normalize')


@infer_rule(*_SAME_SHAPE_UNARY)
def _unary(ctx):
    x = ctx.require('x')
    return {'Out': VarInfo(x.shape, x.dtype)}


@infer_rule('prelu')
def _prelu(ctx):
    x = ctx.require('x')
    return {'Out': VarInfo(x.shape, x.dtype)}


@infer_rule('softmax', 'log_softmax')
def _softmax(ctx):
    x = ctx.require('x')
    ax = ctx.attr('axis', -1)
    if x.shape is not None and isinstance(ax, int) \
            and not -len(x.shape) <= ax < len(x.shape):
        raise InferError(f'softmax axis {ax} out of range for '
                         f'rank-{len(x.shape)} input', kind='bad-attr')
    return {'Out': VarInfo(x.shape, x.dtype)}


@infer_rule('dropout')
def _dropout(ctx):
    x = ctx.require('x')
    p = ctx.attr('dropout_prob', 0.5)
    if not isinstance(p, (int, float)) or not 0.0 <= float(p) <= 1.0:
        raise InferError(f'dropout_prob must be in [0, 1], got {p!r}',
                         kind='bad-attr')
    return {'Out': VarInfo(x.shape, x.dtype)}


@infer_rule('cast')
def _cast(ctx):
    x = ctx.require('x')
    from ..core.dtypes import convert_dtype
    try:
        dtype = convert_dtype(ctx.require_attr('dtype'))
    except TypeError as e:
        raise InferError(str(e), kind='bad-attr')
    return {'Out': VarInfo(x.shape, dtype)}


_COMPARE = ('equal', 'not_equal', 'less_than', 'less_equal', 'greater_than',
            'greater_equal', 'logical_and', 'logical_or', 'logical_xor')


@infer_rule(*_COMPARE)
def _compare(ctx):
    x, y = ctx.require('x'), ctx.require('y')
    shape = (broadcast_shapes(x.shape, y.shape)
             if x.shape is not None and y.shape is not None else None)
    return {'Out': VarInfo(shape, 'bool')}


@infer_rule('logical_not', 'isfinite', 'has_inf', 'has_nan')
def _bool_unary(ctx):
    x = ctx.require('x')
    if ctx.op.type == 'logical_not':
        return {'Out': VarInfo(x.shape, 'bool')}
    return {'Out': VarInfo((), 'bool')}


# ---------------------------------------------------------------------------
# rules: matmul family / reductions
# ---------------------------------------------------------------------------

@infer_rule('matmul')
def _matmul(ctx):
    x, y = ctx.require('x'), ctx.require('y')
    if x.shape is None or y.shape is None:
        return {'Out': VarInfo(None, promote(x.dtype, y.dtype))}
    xs = list(x.shape)
    ys = list(y.shape)
    if ctx.attr('transpose_x', False) and len(xs) > 1:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if ctx.attr('transpose_y', False) and len(ys) > 1:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    if not xs or not ys:
        raise InferError('matmul operands must have rank >= 1')
    if len(xs) == 1 and len(ys) == 1:
        if not dims_agree(xs[0], ys[0]):
            raise InferError(f'matmul contraction dims differ: '
                             f'{xs[0]} vs {ys[0]}')
        return {'Out': VarInfo((), promote(x.dtype, y.dtype))}
    k_x = xs[-1]
    k_y = ys[-2] if len(ys) >= 2 else ys[0]
    if not dims_agree(k_x, k_y):
        raise InferError(
            f'matmul contraction dims differ: x{tuple(x.shape)} '
            f'(K={k_x}) vs y{tuple(y.shape)} (K={k_y})')
    if len(ys) == 1:
        out = tuple(xs[:-1])
    elif len(xs) == 1:
        out = tuple(ys[:-2] + ys[-1:])
    else:
        batch = broadcast_shapes(tuple(xs[:-2]), tuple(ys[:-2]),
                                 'matmul batch dims')
        out = (None if batch is None
               else batch + (xs[-2], ys[-1]))
    return {'Out': VarInfo(out, promote(x.dtype, y.dtype))}


@infer_rule('mul')
def _mul(ctx):
    x, y = ctx.require('x'), ctx.require('y')
    xcd = ctx.attr('x_num_col_dims', 1)
    ycd = ctx.attr('y_num_col_dims', 1)
    if x.shape is None or y.shape is None:
        return {'Out': VarInfo(None, promote(x.dtype, y.dtype))}
    xs, ys = x.shape, y.shape
    if not 0 < xcd < max(len(xs), 1) + 1 or ycd < 1 or ycd > len(ys):
        raise InferError(
            f'mul x_num_col_dims={xcd}/y_num_col_dims={ycd} invalid for '
            f'x{tuple(xs)} y{tuple(ys)}', kind='bad-attr')

    def prod(dims):
        if any(not known(d) for d in dims):
            return UNKNOWN
        return int(np.prod(dims, dtype=np.int64)) if dims else 1

    k_x, k_y = prod(xs[xcd:]), prod(ys[:ycd])
    if not dims_agree(k_x, k_y):
        raise InferError(
            f'mul inner dims differ: x{tuple(xs)} flattens to K={k_x}, '
            f'y{tuple(ys)} to K={k_y}')
    return {'Out': VarInfo(tuple(xs[:xcd]) + tuple(ys[ycd:]),
                           promote(x.dtype, y.dtype))}


@infer_rule('dot')
def _dot(ctx):
    x, y = ctx.require('x'), ctx.require('y')
    if x.shape is not None and y.shape is not None \
            and not shapes_agree(x, y):
        raise InferError(f'dot operands differ: {x.shape} vs {y.shape}')
    return {'Out': VarInfo((1,), promote(x.dtype, y.dtype))}


def _reduced_shape(shape, dim, keep_dim, reduce_all):
    if shape is None:
        return None
    nd = len(shape)
    if reduce_all or dim is None:
        axes = tuple(range(nd))
    else:
        axes = (dim,) if isinstance(dim, int) else tuple(dim)
        for a in axes:
            if not -nd <= a < nd:
                raise InferError(f'reduce dim {a} out of range for '
                                 f'rank-{nd} input', kind='bad-attr')
        axes = tuple(a % nd for a in axes)
    if keep_dim:
        return tuple(1 if i in axes else s for i, s in enumerate(shape))
    return tuple(s for i, s in enumerate(shape) if i not in axes)


_REDUCES = ('reduce_sum', 'reduce_mean', 'reduce_max', 'reduce_min',
            'reduce_prod', 'reduce_all', 'reduce_any')


@infer_rule(*_REDUCES)
def _reduce(ctx):
    x = ctx.require('x')
    shape = _reduced_shape(x.shape, ctx.attr('dim'),
                           ctx.attr('keep_dim', False),
                           ctx.attr('reduce_all', False))
    dtype = 'bool' if ctx.op.type in ('reduce_all', 'reduce_any') else x.dtype
    return {'Out': VarInfo(shape, dtype)}


@infer_rule('logsumexp')
def _logsumexp(ctx):
    x = ctx.require('x')
    return {'Out': VarInfo(_reduced_shape(x.shape, ctx.attr('dim'),
                                          ctx.attr('keep_dim', False),
                                          False), x.dtype)}


@infer_rule('mean')
def _mean(ctx):
    x = ctx.require('x')
    return {'Out': VarInfo((), x.dtype)}


@infer_rule('cumsum')
def _cumsum(ctx):
    x = ctx.require('x')
    if ctx.attr('axis') is None or ctx.attr('flatten', False):
        n = x.numel()
        return {'Out': VarInfo((n if n is not None else UNKNOWN,), x.dtype)}
    return {'Out': VarInfo(x.shape, x.dtype)}


@infer_rule('sum')
def _sum_variadic(ctx):
    xs = ctx.inputs('xs')
    if not xs:
        raise InferError('sum needs at least one input', kind='bad-attr')
    out = xs[0]
    for x in xs[1:]:
        if not shapes_agree(out, x):
            raise InferError(
                f'sum operands have incompatible shapes: '
                f'{out.shape} vs {x.shape}')
        out = VarInfo(out.shape if out.shape is not None else x.shape,
                      promote(out.dtype, x.dtype))
    return {'Out': out}


# ---------------------------------------------------------------------------
# rules: shape manipulation
# ---------------------------------------------------------------------------

@infer_rule('reshape')
def _reshape(ctx):
    x = ctx.require('x')
    spec = list(ctx.require_attr('shape'))
    if spec.count(-1) > 1:
        raise InferError(f'reshape shape {spec} has more than one -1',
                         kind='bad-attr')
    out = []
    for i, s in enumerate(spec):
        if s == 0:                      # paddle: copy input dim i
            if x.shape is None or i >= len(x.shape):
                out.append(UNKNOWN)
            else:
                out.append(x.shape[i])
        elif s == -1:
            out.append(UNKNOWN)         # refined below when provable
        elif isinstance(s, int) and s > 0:
            out.append(s)
        else:
            raise InferError(f'reshape shape entry {s!r} invalid',
                             kind='bad-attr')
    n_in = x.numel()
    if -1 in spec:
        rest = [d for d in out if known(d)]
        if len(rest) == len(out) - 1 and n_in is not None:
            prod = int(np.prod(rest, dtype=np.int64)) if rest else 1
            if prod == 0 or n_in % prod != 0:
                raise InferError(
                    f'reshape cannot infer -1: {n_in} elements do not '
                    f'divide into {spec}')
            out[out.index(UNKNOWN)] = n_in // prod
    elif n_in is not None and all(known(d) for d in out):
        n_out = int(np.prod(out, dtype=np.int64)) if out else 1
        if n_in != n_out:
            raise InferError(
                f'reshape changes element count: {x.display_shape()} '
                f'({n_in} elems) -> {spec} ({n_out} elems)')
    return {'Out': VarInfo(tuple(out), x.dtype)}


@infer_rule('transpose')
def _transpose(ctx):
    x = ctx.require('x')
    perm = list(ctx.require_attr('perm'))
    if x.shape is None:
        return {'Out': VarInfo(None, x.dtype)}
    if sorted(p % len(perm) for p in perm) != list(range(len(x.shape))):
        raise InferError(
            f'transpose perm {perm} is not a permutation of rank '
            f'{len(x.shape)}', kind='bad-attr')
    return {'Out': VarInfo(tuple(x.shape[p] for p in perm), x.dtype)}


@infer_rule('squeeze')
def _squeeze(ctx):
    x = ctx.require('x')
    axes = ctx.attr('axes') or None
    if x.shape is None:
        return {'Out': VarInfo(None, x.dtype)}
    nd = len(x.shape)
    if not axes:
        out = tuple(s for s in x.shape if not (known(s) and s == 1))
    else:
        axes = {a % nd for a in axes}
        for a in axes:
            if known(x.shape[a]) and x.shape[a] != 1:
                raise InferError(
                    f'squeeze axis {a} has size {x.shape[a]} != 1',
                    kind='bad-attr')
        out = tuple(s for i, s in enumerate(x.shape) if i not in axes)
    return {'Out': VarInfo(out, x.dtype)}


@infer_rule('unsqueeze')
def _unsqueeze(ctx):
    x = ctx.require('x')
    axes = ctx.require_attr('axes')
    axes = [axes] if isinstance(axes, int) else list(axes)
    if x.shape is None:
        return {'Out': VarInfo(None, x.dtype)}
    out = list(x.shape)
    for a in sorted(axes):
        out.insert(a if a >= 0 else a + len(out) + 1, 1)
    return {'Out': VarInfo(tuple(out), x.dtype)}


@infer_rule('concat')
def _concat(ctx):
    xs = ctx.inputs('xs')
    if not xs:
        raise InferError('concat needs at least one input', kind='bad-attr')
    axis = ctx.attr('axis', 0)
    dtype = xs[0].dtype
    for x in xs[1:]:
        dtype = promote(dtype, x.dtype)
    ranks = {len(x.shape) for x in xs if x.shape is not None}
    if len(ranks) > 1:
        raise InferError(f'concat inputs have different ranks: {ranks}')
    if not ranks:
        return {'Out': VarInfo(None, dtype)}
    nd = ranks.pop()
    if not -nd <= axis < nd:
        raise InferError(f'concat axis {axis} out of range for rank {nd}',
                         kind='bad-attr')
    axis %= nd
    out = [UNKNOWN] * nd
    cat = 0                      # becomes UNKNOWN on the first unknown part
    for x in xs:
        if x.shape is None:
            cat = UNKNOWN
            continue
        for i in range(nd):
            if i == axis:
                continue
            if not dims_agree(out[i], x.shape[i]):
                raise InferError(
                    f'concat non-axis dim {i} differs across inputs: '
                    f'{out[i]} vs {x.shape[i]}')
            out[i] = merge_dim(out[i], x.shape[i])
        if known(cat) and known(x.shape[axis]):
            cat = cat + x.shape[axis]
        else:
            cat = UNKNOWN
    out[axis] = cat
    return {'Out': VarInfo(tuple(out), dtype)}


@infer_rule('split')
def _split(ctx):
    x = ctx.require('x')
    num = ctx.require_attr('num_or_sections')
    n_out = len(ctx.op.outputs.get('Out', []))
    if x.shape is None:
        return {'Out': [VarInfo(None, x.dtype)] * n_out}
    nd = len(x.shape)
    dim = ctx.attr('dim', -1)
    if not -nd <= dim < nd:
        raise InferError(f'split dim {dim} out of range for rank {nd}',
                         kind='bad-attr')
    dim %= nd
    total = x.shape[dim]
    outs = []
    if isinstance(num, int):
        if num <= 0:
            raise InferError(f'split num {num} must be > 0', kind='bad-attr')
        if known(total) and total % num != 0:
            raise InferError(
                f'split cannot divide dim {dim} of size {total} into '
                f'{num} equal parts')
        part = total // num if known(total) else UNKNOWN
        outs = [VarInfo(x.shape[:dim] + (part,) + x.shape[dim + 1:],
                        x.dtype) for _ in range(num)]
    else:
        sizes = list(num)
        free = [s for s in sizes if s in (-1, None)]
        if len(free) > 1:
            raise InferError(f'split sections {sizes} have more than one -1',
                             kind='bad-attr')
        fixed = sum(s for s in sizes if s not in (-1, None))
        for s in sizes:
            if s in (-1, None):
                part = (total - fixed) if known(total) else UNKNOWN
            else:
                part = s
            outs.append(VarInfo(x.shape[:dim] + (part,) + x.shape[dim + 1:],
                                x.dtype))
        if known(total) and not free and fixed != total:
            raise InferError(
                f'split sections {sizes} sum to {fixed}, dim {dim} has '
                f'size {total}')
    return {'Out': outs}


@infer_rule('stack')
def _stack(ctx):
    xs = ctx.inputs('xs')
    if not xs:
        raise InferError('stack needs at least one input', kind='bad-attr')
    axis = ctx.attr('axis', 0)
    base = next((x for x in xs if x.shape is not None), None)
    dtype = xs[0].dtype
    for x in xs[1:]:
        if base is not None and x.shape is not None \
                and not shapes_agree(x, base):
            raise InferError(
                f'stack inputs have incompatible shapes: {base.shape} '
                f'vs {x.shape}')
        dtype = promote(dtype, x.dtype)
    if base is None:
        return {'Out': VarInfo(None, dtype)}
    out = list(base.shape)
    out.insert(axis if axis >= 0 else axis + len(out) + 1, len(xs))
    return {'Out': VarInfo(tuple(out), dtype)}


@infer_rule('unstack')
def _unstack(ctx):
    x = ctx.require('x')
    axis = ctx.attr('axis', 0)
    n_out = len(ctx.op.outputs.get('Y', []))
    if x.shape is None:
        return {'Y': [VarInfo(None, x.dtype)] * n_out}
    out = x.shape[:axis % len(x.shape)] + x.shape[axis % len(x.shape) + 1:]
    return {'Y': [VarInfo(out, x.dtype)] * n_out}


@infer_rule('slice')
def _slice(ctx):
    x = ctx.require('x')
    axes = ctx.require_attr('axes')
    starts, ends = ctx.require_attr('starts'), ctx.require_attr('ends')
    if x.shape is None:
        return {'Out': VarInfo(None, x.dtype)}
    out = list(x.shape)
    for ax, st, en in zip(axes, starts, ends):
        d = out[ax]
        if known(d):
            lo = st if st >= 0 else max(d + st, 0)
            hi = min(en if en >= 0 else d + en, d)
            out[ax] = max(hi - min(lo, d), 0)
        else:
            out[ax] = UNKNOWN
    return {'Out': VarInfo(tuple(out), x.dtype)}


@infer_rule('flatten', 'flatten2')
def _flatten(ctx):
    x = ctx.require('x')
    axis = ctx.attr('axis', 1)
    if x.shape is None:
        return {'Out': VarInfo((UNKNOWN, UNKNOWN), x.dtype)}
    lead_dims = x.shape[:axis] if axis > 0 else ()
    tail_dims = x.shape[axis:] if axis > 0 else x.shape

    def prod(dims):
        if any(not known(d) for d in dims):
            return UNKNOWN
        return int(np.prod(dims, dtype=np.int64)) if dims else 1

    return {'Out': VarInfo((prod(lead_dims) if axis > 0 else 1,
                            prod(tail_dims)), x.dtype)}


@infer_rule('expand')
def _expand(ctx):
    x = ctx.require('x')
    times = list(ctx.require_attr('expand_times'))
    if x.shape is None:
        return {'Out': VarInfo(None, x.dtype)}
    # jnp.tile semantics: times aligned to the trailing dims
    shape = (1,) * max(len(times) - len(x.shape), 0) + x.shape
    times = [1] * max(len(shape) - len(times), 0) + times
    out = tuple(s * t if known(s) else UNKNOWN
                for s, t in zip(shape, times))
    return {'Out': VarInfo(out, x.dtype)}


@infer_rule('gather')
def _gather(ctx):
    x, idx = ctx.require('x'), ctx.require('index')
    if x.shape is None or idx.shape is None:
        return {'Out': VarInfo(None, x.dtype)}
    ishape = idx.shape
    if len(ishape) == 2 and known(ishape[1]) and ishape[1] == 1:
        ishape = ishape[:1]
    return {'Out': VarInfo(ishape + x.shape[1:], x.dtype)}


@infer_rule('one_hot')
def _one_hot(ctx):
    x = ctx.require('x')
    depth = ctx.require_attr('depth')
    if not isinstance(depth, int) or depth <= 0:
        raise InferError(f'one_hot depth {depth!r} must be a positive int',
                         kind='bad-attr')
    if x.shape is None:
        return {'Out': VarInfo(None, 'float32')}
    shape = x.shape
    if len(shape) >= 2 and known(shape[-1]) and shape[-1] == 1:
        shape = shape[:-1]
    return {'Out': VarInfo(shape + (depth,), 'float32')}


@infer_rule('lookup_table')
def _lookup_table(ctx):
    w, ids = ctx.require('w'), ctx.require('ids')
    if w.shape is not None and len(w.shape) != 2:
        raise InferError(f'lookup_table weight must be rank 2, got '
                         f'{w.display_shape()}')
    emb = w.shape[1] if w.shape is not None else UNKNOWN
    if ids.shape is None:
        return {'Out': VarInfo(None, w.dtype)}
    ishape = ids.shape
    if len(ishape) >= 2 and known(ishape[-1]) and ishape[-1] == 1:
        ishape = ishape[:-1]
    return {'Out': VarInfo(ishape + (emb,), w.dtype)}


@infer_rule('top_k')
def _top_k(ctx):
    x = ctx.require('x')
    k = ctx.require_attr('k')
    if x.shape is None:
        return {'Out': VarInfo(None, x.dtype),
                'Indices': VarInfo(None, 'int64')}
    last = x.shape[-1]
    if known(last) and isinstance(k, int) and k > last:
        raise InferError(f'top_k k={k} exceeds last dim {last}',
                         kind='bad-attr')
    out = x.shape[:-1] + (k if isinstance(k, int) else UNKNOWN,)
    return {'Out': VarInfo(out, x.dtype), 'Indices': VarInfo(out, 'int64')}


@infer_rule('arg_max', 'arg_min')
def _argminmax(ctx):
    x = ctx.require('x')
    axis = ctx.attr('axis', 0)
    from ..core.dtypes import convert_dtype
    dtype = convert_dtype(ctx.attr('dtype', 'int64'))
    if x.shape is None:
        return {'Out': VarInfo(None, dtype)}
    nd = len(x.shape)
    if not -nd <= axis < nd:
        raise InferError(f'arg_max axis {axis} out of range for rank {nd}',
                         kind='bad-attr')
    if ctx.attr('keepdims', False):
        out = tuple(1 if i == axis % nd else s
                    for i, s in enumerate(x.shape))
    else:
        out = tuple(s for i, s in enumerate(x.shape) if i != axis % nd)
    return {'Out': VarInfo(out, dtype)}


@infer_rule('where')
def _where(ctx):
    c = ctx.require('cond')
    x, y = ctx.require('x'), ctx.require('y')
    shape = broadcast_shapes(broadcast_shapes(c.shape, x.shape),
                             y.shape) \
        if None not in (c.shape, x.shape, y.shape) else None
    return {'Out': VarInfo(shape, promote(x.dtype, y.dtype))}


@infer_rule('fill_constant')
def _fill_constant(ctx):
    from ..core.dtypes import convert_dtype
    shape = ctx.require_attr('shape')
    try:
        dtype = convert_dtype(ctx.attr('dtype', 'float32'))
    except TypeError as e:
        raise InferError(str(e), kind='bad-attr')
    if 'value' not in ctx.op.attrs:
        raise InferError('fill_constant requires a value attr',
                         kind='bad-attr')
    return {'Out': VarInfo(tuple(shape), dtype)}


@infer_rule('fill_constant_batch_size_like')
def _fill_batch_like(ctx):
    from ..core.dtypes import convert_dtype
    ref = ctx.require('ref')
    shape = list(ctx.require_attr('shape'))
    dtype = convert_dtype(ctx.attr('dtype', 'float32'))
    in_idx = ctx.attr('input_dim_idx', 0)
    out_idx = ctx.attr('output_dim_idx', 0)
    shape[out_idx] = (ref.shape[in_idx]
                      if ref.shape is not None and in_idx < len(ref.shape)
                      else UNKNOWN)
    return {'Out': VarInfo(tuple(shape), dtype)}


@infer_rule('fill_any_like')
def _fill_any_like(ctx):
    from ..core.dtypes import convert_dtype
    x = ctx.require('x')
    dt = ctx.attr('dtype')
    return {'Out': VarInfo(x.shape,
                           convert_dtype(dt) if dt is not None else x.dtype)}


@infer_rule('shape')
def _shape_op(ctx):
    x = ctx.require('x')
    return {'Out': VarInfo((len(x.shape) if x.shape is not None
                            else UNKNOWN,), 'int32')}


@infer_rule('pad')
def _pad(ctx):
    x = ctx.require('x')
    paddings = ctx.require_attr('paddings')
    if x.shape is None:
        return {'Out': VarInfo(None, x.dtype)}
    if len(paddings) != 2 * len(x.shape):
        raise InferError(
            f'pad expects {2 * len(x.shape)} padding entries for rank '
            f'{len(x.shape)}, got {len(paddings)}', kind='bad-attr')
    out = tuple(s + paddings[2 * i] + paddings[2 * i + 1] if known(s)
                else UNKNOWN for i, s in enumerate(x.shape))
    return {'Out': VarInfo(out, x.dtype)}


# ---------------------------------------------------------------------------
# rules: nn
# ---------------------------------------------------------------------------

def _conv_out_dim(in_dim, k, stride, pad_lo, pad_hi, dilation):
    if not known(in_dim):
        return UNKNOWN
    eff = (k - 1) * dilation + 1
    return (in_dim + pad_lo + pad_hi - eff) // stride + 1


def _pair(v, n=2):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


@infer_rule('conv2d')
def _conv2d(ctx):
    x, w = ctx.require('x'), ctx.require('weight')
    dtype = promote(x.dtype, w.dtype) if x.dtype != w.dtype else x.dtype
    if is_float(x.dtype) and is_float(w.dtype) and x.dtype != w.dtype:
        dtype = w.dtype          # _match_weight_dtype: compute in w's dtype
    if x.shape is None or w.shape is None:
        return {'Out': VarInfo(None, dtype)}
    if len(x.shape) != 4 or len(w.shape) != 4:
        raise InferError(
            f'conv2d expects rank-4 input and weight, got '
            f'x{x.display_shape()} w{w.display_shape()}')
    fmt = ctx.attr('data_format', 'NCHW')
    groups = ctx.attr('groups', 1) or 1
    n, c, h, wd = (x.shape if fmt == 'NCHW'
                   else (x.shape[0], x.shape[3], x.shape[1], x.shape[2]))
    oc, ic, kh, kw = w.shape      # weights always OIHW
    if known(c) and known(ic) and c != ic * groups:
        raise InferError(
            f'conv2d channel mismatch: input has {c} channels, weight '
            f'expects {ic} × groups={groups}')
    stride = _pair(ctx.attr('stride', 1))
    dil = _pair(ctx.attr('dilation', 1))
    padding = ctx.attr('padding', 0)
    if isinstance(padding, str):
        p = padding.upper()
        if p == 'SAME':
            oh = -(-h // stride[0]) if known(h) else UNKNOWN
            ow = -(-wd // stride[1]) if known(wd) else UNKNOWN
        elif p == 'VALID':
            oh = _conv_out_dim(h, kh, stride[0], 0, 0, dil[0]) \
                if known(kh) else UNKNOWN
            ow = _conv_out_dim(wd, kw, stride[1], 0, 0, dil[1]) \
                if known(kw) else UNKNOWN
        else:
            raise InferError(f'conv2d padding {padding!r} invalid',
                             kind='bad-attr')
    else:
        pp = _pair(padding)
        pads = ([(pp[0], pp[0]), (pp[1], pp[1])] if len(pp) == 2
                else [(pp[0], pp[1]), (pp[2], pp[3])])
        oh = _conv_out_dim(h, kh, stride[0], *pads[0], dil[0]) \
            if known(kh) else UNKNOWN
        ow = _conv_out_dim(wd, kw, stride[1], *pads[1], dil[1]) \
            if known(kw) else UNKNOWN
    if isinstance(oh, int) and oh <= 0 or isinstance(ow, int) and ow <= 0:
        raise InferError(
            f'conv2d output spatial dims are non-positive: '
            f'({oh}, {ow}) from x{x.display_shape()} w{w.display_shape()}')
    out = ((n, oc, oh, ow) if fmt == 'NCHW' else (n, oh, ow, oc))
    return {'Out': VarInfo(out, dtype)}


@infer_rule('pool2d')
def _pool2d(ctx):
    x = ctx.require('x')
    if x.shape is None:
        return {'Out': VarInfo(None, x.dtype)}
    if len(x.shape) != 4:
        raise InferError(f'pool2d expects rank-4 input, got '
                         f'{x.display_shape()}')
    fmt = ctx.attr('data_format', 'NCHW')
    n, c, h, w = (x.shape if fmt == 'NCHW'
                  else (x.shape[0], x.shape[3], x.shape[1], x.shape[2]))
    if ctx.attr('global_pooling', False) or ctx.attr('pool_size', -1) in (
            -1, (-1, -1), [-1, -1]):
        oh = ow = 1
    else:
        ks = _pair(ctx.attr('pool_size'))
        st = _pair(ctx.attr('pool_stride', 1))
        pd = _pair(ctx.attr('pool_padding', 0))
        ceil = ctx.attr('ceil_mode', False)

        def odim(d, k, s, p):
            if not known(d):
                return UNKNOWN
            num = d + 2 * p - k
            return (-(-num // s) if ceil else num // s) + 1

        oh, ow = odim(h, ks[0], st[0], pd[0]), odim(w, ks[1], st[1], pd[1])
    out = ((n, c, oh, ow) if fmt == 'NCHW' else (n, oh, ow, c))
    return {'Out': VarInfo(out, x.dtype)}


@infer_rule('adaptive_pool2d')
def _adaptive_pool2d(ctx):
    x = ctx.require('x')
    oh, ow = _pair(ctx.require_attr('pool_size'))
    if x.shape is None:
        return {'Out': VarInfo(None, x.dtype)}
    n, c = x.shape[0], x.shape[1]
    return {'Out': VarInfo((n, c, oh, ow), x.dtype)}


@infer_rule('batch_norm')
def _batch_norm(ctx):
    x = ctx.require('x')
    mean, var = ctx.require('mean'), ctx.require('variance')
    layout = ctx.attr('data_layout', 'NCHW')
    if x.shape is not None and len(x.shape) >= 2:
        c = (x.shape[1] if layout == 'NCHW' and len(x.shape) > 2
             else x.shape[-1])
        for slot, s in (('scale', ctx.input('scale')),
                        ('bias', ctx.input('bias')),
                        ('mean', mean), ('variance', var)):
            if s is not None and s.shape is not None and len(s.shape) == 1 \
                    and not dims_agree(s.shape[0], c):
                raise InferError(
                    f'batch_norm {slot} has {s.shape[0]} channels, input '
                    f'has {c}')
    return {'Y': VarInfo(x.shape, x.dtype),
            'MeanOut': VarInfo(mean.shape, mean.dtype),
            'VarianceOut': VarInfo(var.shape, var.dtype)}


@infer_rule('layer_norm', 'instance_norm', 'group_norm', 'lrn')
def _same_as_x_norm(ctx):
    x = ctx.require('x')
    return {'Out': VarInfo(x.shape, x.dtype)}


# ---------------------------------------------------------------------------
# rules: losses / metrics
# ---------------------------------------------------------------------------

@infer_rule('softmax_with_cross_entropy')
def _softmax_ce(ctx):
    logits, label = ctx.require('logits'), ctx.require('label')
    axis = ctx.attr('axis', -1)
    soft = ctx.attr('soft_label', False)
    if logits.shape is None:
        return {'Loss': VarInfo(None, logits.dtype),
                'Softmax': VarInfo(None, logits.dtype)}
    nd = len(logits.shape)
    ax = axis % nd if -nd <= axis < nd else None
    if ax is None:
        raise InferError(f'softmax_with_cross_entropy axis {axis} out of '
                         f'range for rank {nd}', kind='bad-attr')
    if soft:
        if label.shape is not None \
                and not shapes_agree(label, logits):
            raise InferError(
                f'soft_label=True requires label shape == logits shape: '
                f'{label.display_shape()} vs {logits.display_shape()}')
        if label.dtype is not None and not is_float(label.dtype):
            raise InferError(
                f'soft_label=True requires a float label, got '
                f'{label.dtype}', kind='dtype-mismatch')
    elif label.dtype is not None and is_float(label.dtype):
        raise InferError(
            f'hard-label cross entropy requires an integer label, got '
            f'{label.dtype} (set soft_label=True for distributions)',
            kind='dtype-mismatch')
    loss_shape = tuple(1 if i == ax else s
                       for i, s in enumerate(logits.shape))
    return {'Loss': VarInfo(loss_shape, logits.dtype),
            'Softmax': VarInfo(logits.shape, logits.dtype)}


@infer_rule('cross_entropy')
def _cross_entropy(ctx):
    x = ctx.require('x')
    if x.shape is None:
        return {'Out': VarInfo(None, x.dtype)}
    return {'Out': VarInfo(x.shape[:-1] + (1,), x.dtype)}


@infer_rule('square_error_cost')
def _square_error(ctx):
    # the kernel computes jnp broadcast x - label, so the rule broadcasts
    # too (stricter-than-kernel rules would reject working programs)
    x, y = ctx.require('x'), ctx.require('label')
    shape = (broadcast_shapes(x.shape, y.shape, 'input/label')
             if x.shape is not None and y.shape is not None else None)
    return {'Out': VarInfo(shape, promote(x.dtype, y.dtype))}


@infer_rule('sigmoid_cross_entropy_with_logits')
def _sigmoid_ce(ctx):
    x = ctx.require('x')
    return {'Out': VarInfo(x.shape, x.dtype)}


@infer_rule('accuracy')
def _accuracy(ctx):
    return {'Out': VarInfo((), 'float32'),
            'Correct': VarInfo((), 'int64'),
            'Total': VarInfo((), 'int64')}


# ---------------------------------------------------------------------------
# rules: optimizer updates (outputs mirror their state inputs)
# ---------------------------------------------------------------------------

# op type → {output slot: input slot whose info it mirrors}
_OPT_MIRROR = {
    'sgd': {'ParamOut': 'param'},
    'momentum': {'ParamOut': 'param', 'VelocityOut': 'velocity'},
    'lars_momentum': {'ParamOut': 'param', 'VelocityOut': 'velocity'},
    'adam': {'ParamOut': 'param', 'Moment1Out': 'moment1',
             'Moment2Out': 'moment2', 'Beta1PowOut': 'beta1_pow',
             'Beta2PowOut': 'beta2_pow'},
    'adamax': {'ParamOut': 'param', 'MomentOut': 'moment',
               'InfNormOut': 'inf_norm', 'Beta1PowOut': 'beta1_pow'},
    'adagrad': {'ParamOut': 'param', 'MomentOut': 'moment'},
    'decayed_adagrad': {'ParamOut': 'param', 'MomentOut': 'moment'},
    'adadelta': {'ParamOut': 'param', 'AvgSquaredGradOut': 'avg_squared_grad',
                 'AvgSquaredUpdateOut': 'avg_squared_update'},
    'rmsprop': {'ParamOut': 'param', 'MomentOut': 'moment',
                'MeanSquareOut': 'mean_square', 'MeanGradOut': 'mean_grad'},
    'ftrl': {'ParamOut': 'param', 'SquaredAccumOut': 'squared_accum',
             'LinearAccumOut': 'linear_accum'},
    'lamb': {'ParamOut': 'param', 'Moment1Out': 'moment1',
             'Moment2Out': 'moment2', 'Beta1PowOut': 'beta1_pow',
             'Beta2PowOut': 'beta2_pow'},
    'dpsgd': {'ParamOut': 'param'},
}


def _opt_rule(ctx):
    mirror = _OPT_MIRROR[ctx.op.type]
    param = ctx.input('param')
    grad = ctx.input('grad')
    if param is not None and grad is not None \
            and not shapes_agree(param, grad):
        raise InferError(
            f'{ctx.op.type} param/grad shapes differ: '
            f'{param.display_shape()} vs {grad.display_shape()}')
    out = {}
    for out_slot, in_slot in mirror.items():
        src = ctx.input(in_slot)
        if src is not None:
            out[out_slot] = VarInfo(src.shape, src.dtype)
    return out


for _t in _OPT_MIRROR:
    infer_rule(_t)(_opt_rule)


# rows-only (padded-COO) update ops — docs/SPARSE.md. rows is rank-1
# int, vals rank-2 with the param's embedding width; outputs mirror the
# param/slot inputs exactly like the dense family above.
_SPARSE_OPT_MIRROR = {
    'sparse_sgd': {'ParamOut': 'param'},
    'sparse_momentum': {'ParamOut': 'param', 'VelocityOut': 'velocity'},
    'sparse_adagrad': {'ParamOut': 'param', 'MomentOut': 'moment'},
    'sparse_adam': {'ParamOut': 'param', 'Moment1Out': 'moment1',
                    'Moment2Out': 'moment2', 'Beta1PowOut': 'beta1_pow',
                    'Beta2PowOut': 'beta2_pow'},
}


def _sparse_opt_rule(ctx):
    mirror = _SPARSE_OPT_MIRROR[ctx.op.type]
    param = ctx.input('param')
    rows, vals = ctx.input('rows'), ctx.input('vals')
    if rows is not None and rows.shape is not None and len(rows.shape) != 1:
        raise InferError(
            f'{ctx.op.type} rows must be rank 1 (padded COO row ids), got '
            f'{rows.display_shape()}')
    if vals is not None and vals.shape is not None and len(vals.shape) != 2:
        raise InferError(
            f'{ctx.op.type} vals must be rank 2 (rows × embedding dim), '
            f'got {vals.display_shape()}')
    if rows is not None and vals is not None \
            and rows.shape is not None and vals.shape is not None \
            and known(rows.shape[0]) and known(vals.shape[0]) \
            and rows.shape[0] != vals.shape[0]:
        raise InferError(
            f'{ctx.op.type} rows/vals leading dims differ: '
            f'{rows.display_shape()} vs {vals.display_shape()}')
    if param is not None and vals is not None \
            and param.shape is not None and vals.shape is not None \
            and len(param.shape) == 2 \
            and known(param.shape[1]) and known(vals.shape[1]) \
            and param.shape[1] != vals.shape[1]:
        raise InferError(
            f'{ctx.op.type} vals width {vals.shape[1]} does not match '
            f'table width {param.shape[1]}')
    if param is not None and vals is not None \
            and param.dtype is not None and vals.dtype is not None \
            and param.dtype != vals.dtype:
        raise InferError(
            f'{ctx.op.type} param dtype {param.dtype} vs vals dtype '
            f'{vals.dtype}', kind='dtype-mismatch')
    out = {}
    for out_slot, in_slot in mirror.items():
        src = ctx.input(in_slot)
        if src is not None:
            out[out_slot] = VarInfo(src.shape, src.dtype)
    return out


for _t in _SPARSE_OPT_MIRROR:
    infer_rule(_t)(_sparse_opt_rule)


_FUSED_OPT_MIRROR = {
    'fused_sgd': {'ParamOut': 'params'},
    'fused_momentum': {'ParamOut': 'params', 'VelocityOut': 'velocities'},
    'fused_lars_momentum': {'ParamOut': 'params',
                            'VelocityOut': 'velocities'},
    'fused_adam': {'ParamOut': 'params', 'Moment1Out': 'moment1s',
                   'Moment2Out': 'moment2s'},
}


def _fused_opt_rule(ctx):
    mirror = _FUSED_OPT_MIRROR[ctx.op.type]
    params = ctx.inputs('params')
    grads = ctx.inputs('grads')
    if len(params) != len(grads):
        raise InferError(
            f'{ctx.op.type} has {len(params)} params but {len(grads)} '
            f'grads', kind='bad-attr')
    dtypes = {p.dtype for p in params + grads if p.dtype is not None}
    if len(dtypes) > 1:
        raise InferError(
            f'{ctx.op.type} bundle mixes dtypes {sorted(dtypes)}; the '
            f'flattened multi-tensor update requires one dtype',
            kind='dtype-mismatch')
    for p, g in zip(params, grads):
        if not shapes_agree(p, g):
            raise InferError(
                f'{ctx.op.type} param/grad shapes differ: '
                f'{p.display_shape()} vs {g.display_shape()}')
    out = {}
    for out_slot, in_slot in mirror.items():
        srcs = ctx.inputs(in_slot)
        out[out_slot] = [VarInfo(s.shape, s.dtype) for s in srcs]
    if ctx.op.type == 'fused_adam':
        n = len(params)
        out['Beta1PowOut'] = [VarInfo((1,), 'float32')] * n
        out['Beta2PowOut'] = [VarInfo((1,), 'float32')] * n
    return out


for _t in _FUSED_OPT_MIRROR:
    infer_rule(_t)(_fused_opt_rule)


# ---------------------------------------------------------------------------
# rules: collectives
# ---------------------------------------------------------------------------

_COMM_DTYPES = (None, 'f32', 'bf16', 'int8')


def _check_comm_dtype(ctx):
    cd = ctx.attr('comm_dtype')
    if cd not in _COMM_DTYPES:
        raise InferError(
            f'comm_dtype {cd!r} invalid; expected one of '
            f'{[d for d in _COMM_DTYPES if d]}', kind='bad-attr')


@infer_rule('c_allreduce_sum', 'c_allreduce_max', 'c_allreduce_min',
            'c_allreduce_prod')
def _allreduce(ctx):
    _check_comm_dtype(ctx)
    x = ctx.require('x')
    return {'Out': VarInfo(x.shape, x.dtype)}


@infer_rule('c_allreduce_sum_bucket')
def _allreduce_bucket(ctx):
    _check_comm_dtype(ctx)
    xs = ctx.inputs('xs')
    if len(ctx.op.outputs.get('Out', [])) != len(xs):
        raise InferError(
            f'c_allreduce_sum_bucket has {len(xs)} inputs but '
            f'{len(ctx.op.outputs.get("Out", []))} outputs',
            kind='bad-attr')
    dtypes = {x.dtype for x in xs if x.dtype is not None}
    if len(dtypes) > 1:
        raise InferError(
            f'c_allreduce_sum_bucket mixes operand dtypes '
            f'{sorted(dtypes)}; buckets must be dtype-uniform',
            kind='dtype-mismatch')
    return {'Out': [VarInfo(x.shape, x.dtype) for x in xs]}


# ---------------------------------------------------------------------------
# rules: paged KV-cache attention (serving/decode)
# ---------------------------------------------------------------------------

def _check_kv_scales(ctx):
    """The optional int8-pool dequant scales: one f32 per (head, block,
    position) row — rank 3, matching the pages' leading dims when both are
    known. Typed here so the generic byte model prices a quantized pool as
    1 B/elem payload + 4 B/row scales with no op-specific bytes rule."""
    pages = ctx.input('k_pages')
    for slot in ('k_scales', 'v_scales'):
        sc = ctx.input(slot)
        if sc is None:
            continue
        if sc.dtype is not None and sc.dtype != 'float32':
            raise InferError(
                f'{slot} must be float32 row scales, got {sc.dtype}',
                kind='dtype-mismatch')
        if sc.shape is not None:
            if len(sc.shape) != 3:
                raise InferError(
                    f'{slot} expects rank 3 (H, num_blocks, block_size), '
                    f'got rank {len(sc.shape)}')
            if (pages is not None and pages.shape is not None
                    and len(pages.shape) == 4
                    and tuple(sc.shape) != tuple(pages.shape[:3])):
                raise InferError(
                    f'{slot} shape {tuple(sc.shape)} does not match the '
                    f'pages\' (H, num_blocks, block_size) '
                    f'{tuple(pages.shape[:3])}')


@infer_rule('paged_attention')
def _paged_attention(ctx):
    # decode read: q (S, H, D) -> (S, H, D); multi-query speculative
    # verify: q (S, H, K, D) -> (S, H, K, D). Out always mirrors q.
    q = ctx.require('q')
    if q.shape is not None and len(q.shape) not in (3, 4):
        raise InferError(
            f'paged_attention expects q of rank 3 (decode) or 4 '
            f'(multi-query verify), got rank {len(q.shape)}')
    _check_kv_scales(ctx)
    return {'Out': VarInfo(q.shape, q.dtype)}


@infer_rule('paged_prefill_attention')
def _paged_prefill_attention(ctx):
    q = ctx.require('q')
    if q.shape is not None and len(q.shape) != 4:
        raise InferError(
            f'paged_prefill_attention expects q of rank 4 (1, H, L, D), '
            f'got rank {len(q.shape)}')
    _check_kv_scales(ctx)
    return {'Out': VarInfo(q.shape, q.dtype)}


# ---------------------------------------------------------------------------
# rules: framework-internal ops
# ---------------------------------------------------------------------------

@infer_rule('__constant__')
def _ir_constant(ctx):
    from ..core.dtypes import convert_dtype
    v = np.asarray(ctx.require_attr('value'))
    return {'Out': VarInfo(v.shape, convert_dtype(v.dtype))}


@infer_rule('__init__')
def _ir_init(ctx):
    from ..core.dtypes import convert_dtype
    return {'Out': VarInfo(tuple(ctx.require_attr('shape')),
                           convert_dtype(ctx.attr('dtype', 'float32')))}
