"""Dataflow diagnostics over a Program: the verifier's check suite.

One forward walk per block drives everything: availability tracking
(read-before-write / dangling vars), registry conformance (unknown op
types, missing required input slots), the infer.py rule engine
(shape/dtype mismatch at op inputs, declared-vs-inferred drift), and
the repo-specific consistency lints (collective comm_dtype drift,
``c_allreduce`` under k-step schedules, bucket dtype uniformity, RNG
salt stamps after pass rewrites). A reverse pass afterwards finds dead
writes, dead vars, and donation hazards.

Everything lands as a :class:`~.diagnostics.Diagnostic`; severities
follow the policy in diagnostics.py (errors = cannot lower, warnings =
suspicious/slow, info = coverage notes). ``stage`` tweaks two rules:

- ``'post-pass'`` — an INTERMEDIATE IR-pass output: needs_rng ops must
  carry their ``_rng_salt`` stamp (bitwise pass-on/off RNG contract);
  dead code stays info, because e.g. constant folding deliberately
  leaves orphaned producers for the DCE pass to sweep.
- ``'post-pipeline'`` — the FINAL pipeline output: dead writes/vars
  become warnings (the pipeline ends with DCE; surviving debris means a
  pass left a mess DCE could not see).
- anything else — user-built programs: dead code is info (the DCE pass
  exists precisely to sweep it).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..framework import BACKWARD_OP_TYPE
from .diagnostics import Diagnostic
from . import infer
from .infer import (UNKNOWN, InferError, VarInfo, declared_info, has_rule,
                    infer_op, is_float, seed_env, shapes_agree)

__all__ = ['run_checks']

# executor-interpreted op types that never reach the op registry
_SPECIAL_OPS = frozenset({
    BACKWARD_OP_TYPE, '__init__', '__constant__', '__create_array__',
    '__cond__', '__switch__', '__while__', '__while_legacy__', '__scan__'})

_SUB_BLOCK_ATTRS = ('true_block', 'false_block', 'cond_block', 'body_block',
                    'block')

_COLLECTIVE_TYPES = ('c_allreduce_sum', 'c_allreduce_max', 'c_allreduce_min',
                     'c_allreduce_prod', 'c_allreduce_sum_bucket')

_UPDATE_OP_TYPES = frozenset(infer._OPT_MIRROR) | \
    frozenset(infer._FUSED_OPT_MIRROR) | frozenset(infer._SPARSE_OPT_MIRROR)


def _site(op):
    return getattr(op, '_site', None)


def _sub_block_indices(op):
    subs = [op.attrs[a] for a in _SUB_BLOCK_ATTRS if a in op.attrs]
    subs.extend(op.attrs.get('blocks', []))
    return subs


def _op_external_reads(op, program) -> Set[str]:
    """Names an op reads from its enclosing scope: declared inputs plus
    sub-block reads that are not produced earlier inside the sub-block
    (control-flow branches chain onto the outer env — executor._run_block).
    Names the control-flow machinery binds itself before the sub-block
    runs — __scan__'s per-step slices and carried memories (bound from the
    op's X/Init inputs, executor._run_scan) — are not external. Names the
    machinery READS from the outer env — cond/switch `writes` passthrough,
    while-loop carry seeds — are external even when no sub-op reads them
    (mirrors executor._op_read_names)."""
    reads = set(op.input_names())
    for attr in ('writes', 'loop_vars', 'carry'):
        v = op.attrs.get(attr)
        if isinstance(v, (list, tuple)):
            reads.update(x for x in v if isinstance(x, str))
    bound: Set[str] = set()
    if op.type == '__scan__':
        bound |= set(op.attrs.get('slice_names', []))
        bound |= set(op.attrs.get('pre_names', []))
    for bi in _sub_block_indices(op):
        produced: Set[str] = set(bound)
        for sub in program.block(bi).ops:
            reads |= _op_external_reads(sub, program) - produced
            produced |= set(sub.output_names())
    return reads


class _Checker:
    def __init__(self, program, fetch_names, feed_names, stage):
        self.program = program
        self.fetch_names = tuple(fetch_names)
        self.stage = stage
        self.diags: List[Diagnostic] = []
        self.persist = {v.name for v in program.list_vars() if v.persistable}
        self.declared = {v.name for v in program.list_vars()}
        self.data_vars = {v.name for v in program.list_vars() if v.is_data}
        self.roots = self.persist | self.data_vars | set(feed_names)
        self.amp = getattr(program, '_amp_config', None) is not None
        self.comm_dtype_seen: Optional[object] = None
        self.has_kstep_update = self._detect_kstep_update()

    # -- helpers ---------------------------------------------------------

    def emit(self, severity, code, message, op=None, op_index=None,
             block_idx=0, var=None):
        self.diags.append(Diagnostic(
            severity, code, message,
            op_type=op.type if op is not None else None,
            op_index=op_index, block_idx=block_idx, var=var,
            site=_site(op) if op is not None else None, stage=self.stage))

    def _dtype_compatible(self, a, b):
        """IR-level dtype agreement, absorbing the runtime int64→int32
        mapping (core/dtypes.to_jax_dtype) and — under AMP — trace-time
        float casts (executor._amp_cast_args)."""
        if a is None or b is None or a == b:
            return True
        if {a, b} == {'int32', 'int64'}:
            return True
        if self.amp and is_float(a) and is_float(b):
            return True
        return False

    def _detect_kstep_update(self):
        """Whether parameter updates live inside a cond sub-block — the
        gradient-merge / local-SGD k-step schedule shape. Per-step
        c_allreduce sync points are wrong there: the sync must happen once
        per k steps (parallel/fleet.py skips insertion for merge_k > 1)."""
        for op in self.program.global_block().ops:
            if op.type not in ('__cond__', '__switch__'):
                continue
            for bi in _sub_block_indices(op):
                for sub in self.program.block(bi).ops:
                    if sub.type in _UPDATE_OP_TYPES:
                        return True
        return False

    # -- the walk --------------------------------------------------------

    def run(self):
        blk = self.program.global_block()
        env = seed_env(self.program)
        self._walk(blk, env, set(self.roots))
        self._check_dead(blk)
        self._check_donation(blk)
        self._check_sharding(blk)
        return self.diags

    def _walk(self, block, env: Dict[str, VarInfo], available: Set[str]):
        for idx, op in enumerate(block.ops):
            self._check_op(op, idx, block, env, available)
            available |= set(op.output_names())

    def _check_op(self, op, idx, block, env, available):
        bi = block.idx
        # 1. op type resolution
        opdef = None
        if op.type not in _SPECIAL_OPS:
            from ..ops.registry import has_op, get_op
            if not has_op(op.type):
                self.emit('error', 'unknown-op',
                          f"op type {op.type!r} is not a registered op",
                          op, idx, bi)
                return
            opdef = get_op(op.type)

        # 2. reads resolve (read-before-write / dangling)
        for name in sorted(_op_external_reads(op, self.program)):
            if name in available:
                continue
            if name not in self.declared:
                self.emit('error', 'dangling-var',
                          f"op reads {name!r}, which is not declared in "
                          f"any block", op, idx, bi, var=name)
            else:
                self.emit('error', 'read-before-write',
                          f"op reads {name!r} before any op writes it "
                          f"(not a feed, not persistable)",
                          op, idx, bi, var=name)

        # 3. special ops
        if op.type == BACKWARD_OP_TYPE:
            self._check_backward(op, idx, block, env, available)
            return
        if op.type in _SPECIAL_OPS:
            self._check_control_flow(op, idx, block, env, available)
            return

        # 4. required input slots
        for slot in opdef.input_slots:
            if slot not in opdef.optional and not op.inputs.get(slot):
                self.emit('error', 'missing-input',
                          f"required input slot {slot!r} of "
                          f"{op.type!r} is empty", op, idx, bi)

        # 5. mixed-precision inputs (outside AMP: silently-upcasting math)
        if not self.amp:
            fdts = {env[n].dtype if n in env
                    else (declared_info(block.var(n)).dtype
                          if block.has_var(n) else None)
                    for n in op.input_names()}
            fdts = {d for d in fdts if d is not None and is_float(d)}
            if len(fdts) > 1:
                self.emit('warning', 'mixed-float-inputs',
                          f"op mixes float input dtypes {sorted(fdts)} "
                          f"without an AMP config (silent upcast)",
                          op, idx, bi)

        # 6. collective consistency
        if op.type in _COLLECTIVE_TYPES:
            self._check_collective(op, idx, block)

        # 7. RNG salt stamps (pass post-condition only)
        if self.stage in ('post-pass', 'post-pipeline') and opdef.needs_rng \
                and '_rng_salt' not in op.attrs:
            self.emit('warning', 'rng-salt-missing',
                      f"RNG op {op.type!r} lost its _rng_salt stamp in a "
                      f"pass rewrite; its random stream will shift with "
                      f"program position", op, idx, bi)

        # 8. shape/dtype inference + declared-info drift
        self._infer_into(op, idx, block, env)

    def _infer_into(self, op, idx, block, env):
        bi = block.idx
        try:
            result = infer_op(op, env, block)
        except InferError as e:
            self.emit('error', e.kind, str(e), op, idx, bi)
            result = None
        if result is None:
            if not has_rule(op.type):
                self.emit('info', 'no-infer-rule',
                          f"no shape/dtype inference rule for "
                          f"{op.type!r}; propagating declared infos",
                          op, idx, bi)
            for name in op.output_names():
                if block.has_var(name):
                    env[name] = declared_info(block.var(name))
                else:
                    env[name] = VarInfo()
            return
        from ..ops.registry import get_op
        opdef = get_op(op.type)
        for slot in opdef.output_slots:
            names = op.outputs.get(slot, [])
            if not names:
                continue
            res = result.get(slot)
            infos = (list(res) if isinstance(res, (list, tuple))
                     else [res] * len(names))
            for name, info in zip(names, infos):
                if info is None:
                    info = VarInfo()
                self._bind_output(op, idx, block, env, name, info)

    def _bind_output(self, op, idx, block, env, name, info: VarInfo):
        bi = block.idx
        if block.has_var(name):
            decl = declared_info(block.var(name))
            if not shapes_agree(info, decl):
                self.emit('warning', 'shape-decl-mismatch',
                          f"op writes {name!r} with inferred shape "
                          f"{info.display_shape()}, but the var is "
                          f"declared {decl.display_shape()}",
                          op, idx, bi, var=name)
            if not self._dtype_compatible(info.dtype, decl.dtype):
                self.emit('warning', 'dtype-decl-mismatch',
                          f"op writes {name!r} with inferred dtype "
                          f"{info.dtype}, but the var is declared "
                          f"{decl.dtype}", op, idx, bi, var=name)
            # fill unknowns from the declaration (build-time eval_shape)
            if info.shape is None:
                info = VarInfo(decl.shape, info.dtype or decl.dtype,
                               decl.lod_level)
            elif info.dtype is None:
                info = info.with_dtype(decl.dtype)
        env[name] = info

    def _check_backward(self, op, idx, block, env, available):
        bi = block.idx
        loss = op.attrs.get('loss')
        if loss and loss not in available and loss not in self.declared:
            self.emit('error', 'dangling-var',
                      f"backward marker loss {loss!r} is not declared",
                      op, idx, bi, var=loss)
        feeds = self.data_vars | set(self.roots)
        for p in (list(op.attrs.get('params', []))
                  + list(op.attrs.get('sparse_params', []))):
            if p in self.persist or p in feeds or p in available:
                continue
            self.emit('error', 'read-before-write',
                      f"gradient target {p!r} is neither a persistable "
                      f"parameter nor a fed variable", op, idx, bi, var=p)
        # grads mirror their params
        for p, g in zip(op.attrs.get('params', []),
                        op.outputs.get('Grads', [])):
            if block.has_var(p):
                pi = declared_info(block.var(p))
                env[g] = VarInfo(pi.shape, pi.dtype)
        # sparse tables emit a padded-COO pair instead (docs/SPARSE.md):
        # rows (K,) int32 + vals (K, D); K is runtime (bucket ladder)
        for p, r, v in zip(op.attrs.get('sparse_params', []),
                           op.outputs.get('SparseRows', []),
                           op.outputs.get('SparseVals', [])):
            dim, dtype = UNKNOWN, None
            if block.has_var(p):
                pi = declared_info(block.var(p))
                dtype = pi.dtype
                if pi.shape is not None and len(pi.shape) == 2:
                    dim = pi.shape[1]
            env[r] = VarInfo((UNKNOWN,), 'int32')
            env[v] = VarInfo((UNKNOWN, dim), dtype)

    def _check_control_flow(self, op, idx, block, env, available):
        for bi in _sub_block_indices(op):
            sub = self.program.block(bi)
            child_env = dict(env)
            child_avail = set(available) | set(op.output_names())
            # loop carries / scan slices are bound by the executor before
            # the sub-block runs
            for attr in ('loop_vars', 'carry', 'slice_names', 'pre_names',
                         'writes'):
                v = op.attrs.get(attr)
                if isinstance(v, (list, tuple)):
                    child_avail |= {x for x in v if isinstance(x, str)}
            self._walk(sub, child_env, child_avail)
        for name in op.output_names():
            env[name] = (declared_info(block.var(name))
                         if block.has_var(name) else VarInfo())

    def _check_collective(self, op, idx, block):
        bi = block.idx
        cd = op.attrs.get('comm_dtype')
        if cd is not None:
            if self.comm_dtype_seen is None:
                self.comm_dtype_seen = cd
            elif cd != self.comm_dtype_seen:
                self.emit('warning', 'comm-dtype-drift',
                          f"collective comm_dtype {cd!r} differs from "
                          f"{self.comm_dtype_seen!r} seen earlier in this "
                          f"program; gradient sync would mix wire "
                          f"precisions", op, idx, bi)
        if self.has_kstep_update:
            self.emit('warning', 'allreduce-under-kstep',
                      f"per-step {op.type!r} in a program whose parameter "
                      f"updates run under a k-step schedule (gradient "
                      f"merge / local SGD); the sync belongs at the k-step "
                      f"boundary", op, idx, bi)

    # -- post-walk checks ------------------------------------------------

    def _check_dead(self, blk):
        """Reverse liveness: ops none of whose outputs are ever read,
        fetched, or persisted; and vars no op references at all."""
        live = set(self.fetch_names) | self.persist
        dead_sev = 'warning' if self.stage == 'post-pipeline' else 'info'
        marker_used = set()
        for op in blk.ops:
            for attr in ('loss', 'params', 'checkpoints'):
                v = op.attrs.get(attr)
                if isinstance(v, str):
                    marker_used.add(v)
                elif isinstance(v, (list, tuple)):
                    marker_used.update(x for x in v if isinstance(x, str))
        live |= marker_used
        for idx in range(len(blk.ops) - 1, -1, -1):
            op = blk.ops[idx]
            outs = op.output_names()
            if op.type == BACKWARD_OP_TYPE or not outs \
                    or any(o in live for o in outs):
                live |= _op_external_reads(op, self.program)
                continue
            self.emit(dead_sev, 'dead-write',
                      f"no later op reads any output of this op "
                      f"({', '.join(repr(o) for o in outs[:3])}"
                      f"{'…' if len(outs) > 3 else ''}); it is dead code",
                      op, idx, blk.idx)
        referenced = set(self.fetch_names) | marker_used
        for op in blk.ops:
            referenced |= _op_external_reads(op, self.program)
            referenced |= set(op.output_names())
        for name, v in blk.vars.items():
            if name in referenced or name in self.persist or v.is_data:
                continue
            self.emit(dead_sev, 'dead-var',
                      f"var {name!r} is declared in the global block but "
                      f"no op references it", var=name)

    def _check_donation(self, blk):
        """A fetched persistable that the step also WRITES cannot be
        donated — Executor.run keeps it out of the in-place set, so the
        state runs copy-in/copy-out every step (executor.py donation
        guards). Static warning so the cost is visible before runtime."""
        fetch = set(self.fetch_names)
        if not fetch:
            return
        for idx, op in enumerate(blk.ops):
            if op.type == BACKWARD_OP_TYPE:
                continue
            for name in op.output_names():
                if name in fetch and name in self.persist:
                    self.emit('warning', 'donated-fetch',
                              f"persistable {name!r} is both updated by "
                              f"this op and fetched; it will be excluded "
                              f"from buffer donation (copy-in/copy-out "
                              f"every step)", op, idx, blk.idx, var=name)
                    fetch.discard(name)      # one diagnostic per var


    # -- sharding consistency (partitioner-stamped programs) -------------

    def _check_sharding(self, blk):
        """Sharding-consistency diagnostics for programs the partitioner
        stamped (`program._partition_specs` — paddle_tpu/partition):
        every asserted PartitionSpec must fit its var's declared rank
        ('spec-rank-mismatch'), name only mesh axes that exist
        ('spec-unknown-axis'), use each mesh axis at most once per tensor
        ('spec-axis-reuse'), divide every concretely-known sharded dim
        ('spec-indivisible'), and elementwise producer/consumer pairs
        must not assert different axes on the same dim ('spec-conflict').
        Each finding anchors at the var's producer op so the
        construction site points at the model code."""
        specs = getattr(self.program, '_partition_specs', None)
        if not specs:
            return
        mesh_axes = dict(
            getattr(self.program, '_partition_mesh_axes', None) or {})
        producer = {}
        for idx, op in enumerate(blk.ops):
            for n in op.output_names():
                producer.setdefault(n, (op, idx))

        def flat_axes(entry):
            if entry is None:
                return ()
            return tuple(entry) if isinstance(entry, (tuple, list)) \
                else (entry,)

        for name in sorted(specs):
            entries = tuple(specs[name])
            op, idx = producer.get(name, (None, None))
            shape = None
            if blk.has_var(name):
                shape = declared_info(blk.var(name)).shape
            if shape is not None and len(entries) > len(shape):
                self.emit('error', 'spec-rank-mismatch',
                          f"partition spec {entries!r} for {name!r} has "
                          f"{len(entries)} entries but the var is rank "
                          f"{len(shape)}", op, idx, var=name)
                continue
            seen: Set[str] = set()
            for i, entry in enumerate(entries):
                axes = flat_axes(entry)
                span = 1
                for a in axes:
                    if a not in mesh_axes:
                        self.emit('error', 'spec-unknown-axis',
                                  f"partition spec of {name!r} names mesh "
                                  f"axis {a!r}, not an axis of the mesh "
                                  f"{sorted(mesh_axes)}", op, idx, var=name)
                        continue
                    if a in seen:
                        self.emit('error', 'spec-axis-reuse',
                                  f"partition spec of {name!r} uses mesh "
                                  f"axis {a!r} on more than one dim",
                                  op, idx, var=name)
                    seen.add(a)
                    span *= int(mesh_axes[a])
                if span > 1 and shape is not None and i < len(shape):
                    dim = shape[i]
                    if isinstance(dim, int) and dim % span != 0:
                        self.emit('error', 'spec-indivisible',
                                  f"dim {i} of {name!r} is {dim}, not "
                                  f"divisible by the {span}-way sharding "
                                  f"{entry!r}", op, idx, var=name)

        # producer/consumer conflicts: an elementwise op whose two
        # operands positively assert DIFFERENT axes on the same dim
        # cannot satisfy both without a resharding GSPMD would have to
        # invent — the composition the partitioner exists to rule out
        from ..partition.propagation import ELEMENTWISE_BINARY
        for idx, op in enumerate(blk.ops):
            if op.type not in ELEMENTWISE_BINARY:
                continue
            xn = (op.inputs.get('x') or (None,))[0]
            yn = (op.inputs.get('y') or (None,))[0]
            xs, ys = specs.get(xn), specs.get(yn)
            if not xs or not ys or len(xs) != len(ys):
                continue
            for i, (a, b) in enumerate(zip(xs, ys)):
                if a is not None and b is not None \
                        and flat_axes(a) != flat_axes(b):
                    self.emit('error', 'spec-conflict',
                              f"operands {xn!r} ({tuple(xs)!r}) and "
                              f"{yn!r} ({tuple(ys)!r}) of {op.type!r} "
                              f"assert conflicting sharding on dim {i}",
                              op, idx)
                    break


def run_checks(program, fetch_names=(), feed_names=(), stage='pre'):
    """All diagnostics for `program`. `stage` ∈ {'pre', 'pre-lower',
    'post-pass', 'post-pipeline'} — see module docstring."""
    return _Checker(program, fetch_names, feed_names, stage).run()
