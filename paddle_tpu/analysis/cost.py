"""Static per-op FLOP/byte cost model over the VarInfo lattice — zero tracing.

The verifier (infer.py) proves shapes and dtypes for every op the tier-1
recipes emit; this module multiplies those facts into costs *before* XLA
does: a :func:`cost_rule` registry (same shape as ``@infer_rule``) maps op
types to FLOP estimates, and byte traffic falls out of the VarInfos
generically (Σ input bytes read + Σ output bytes written). plan.py folds
the per-op costs into a whole-Program liveness/peak-HBM plan.

Conventions (docs/ANALYSIS.md "Cost model"):

- **Byte widths are RUNTIME widths**, not declared widths: ``int64``
  computes as int32 on device under the default x64-off config
  (core/dtypes.to_jax_dtype), so it costs 4 bytes/elem here too. That is
  what makes the plan's accounting comparable to the executor's measured
  fetch/feed/state byte counters.
- **FLOPs are multiply-add-counted estimates**, not exact instruction
  counts: matmul = 2·M·K·N, conv2d = 2·out·(C_in·kh·kw), elementwise =
  out elems, transcendentals = ``TRANSCENDENTAL_FLOPS``·elems, optimizer
  updates = a per-op factor·param elems (``_OPT_FLOP_FACTORS``). Pure
  data-movement ops (reshape/transpose/concat/…) are 0 FLOPs — their
  cost is the bytes the generic accounting already charges.
- **UNKNOWN dims** (dynamic batch) substitute ``assume_dim`` (callers
  pass the real feed batch when they have one — the executor's plan hook
  does), so a plan over a concrete feed signature is exact.

Coverage contract: every op type with an inference rule has a cost rule
(asserted in tier-1), so anything the 6 verifier recipes emit — pre- or
post-pass-pipeline, ``fused_*`` bundles and collective buckets included
— is costed. Ops without a rule fall back to bytes-only (0 FLOPs) and
are reported by plan.py as coverage gaps, never errors.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from . import infer
from .infer import UNKNOWN, VarInfo, declared_info, known

__all__ = ['OpCost', 'cost_rule', 'has_cost_rule', 'all_cost_rules',
           'dtype_nbytes', 'info_nbytes', 'op_cost', 'CostCtx',
           'TRANSCENDENTAL_FLOPS']

# device (runtime) byte width per canonical dtype name; int64 maps to 4
# because the executor computes it as int32 (to_jax_dtype, x64 off)
_DTYPE_NBYTES = {
    'bool': 1, 'int8': 1, 'uint8': 1, 'int16': 2, 'int64': 4, 'int32': 4,
    'float16': 2, 'bfloat16': 2, 'float32': 4, 'float64': 8,
    'complex64': 8,
}

# cost of one exp/log/tanh-class element relative to one add/mul
TRANSCENDENTAL_FLOPS = 8


def dtype_nbytes(dtype: Optional[str]) -> int:
    """Runtime bytes per element; unknown dtype prices as float32."""
    return _DTYPE_NBYTES.get(dtype, 4)


def info_elems(info: Optional[VarInfo], assume_dim: int = 1) -> int:
    """Element count with UNKNOWN dims priced at `assume_dim`. Rank-unknown
    infos price as one element (a scalar) — coverage gap, never a crash."""
    if info is None or info.shape is None:
        return 1
    n = 1
    for s in info.shape:
        n *= int(s) if known(s) else int(assume_dim)
    return int(n)


def info_nbytes(info: Optional[VarInfo], assume_dim: int = 1) -> int:
    if info is None:
        return 0
    return info_elems(info, assume_dim) * dtype_nbytes(info.dtype)


class OpCost:
    """Cost of one op: FLOPs plus bytes read/written (HBM traffic)."""

    __slots__ = ('flops', 'bytes_in', 'bytes_out')

    def __init__(self, flops=0, bytes_in=0, bytes_out=0):
        self.flops = int(flops)
        self.bytes_in = int(bytes_in)
        self.bytes_out = int(bytes_out)

    @property
    def bytes(self):
        return self.bytes_in + self.bytes_out

    @property
    def flops_per_byte(self):
        """Arithmetic intensity — the remat selector's ranking key."""
        return self.flops / self.bytes if self.bytes else 0.0

    def __repr__(self):
        return (f'OpCost(flops={self.flops}, bytes_in={self.bytes_in}, '
                f'bytes_out={self.bytes_out})')


# ---------------------------------------------------------------------------
# rule registry (one FLOP estimator per op type; bytes are generic)
# ---------------------------------------------------------------------------

_COST_RULES: Dict[str, object] = {}


def cost_rule(*op_types):
    """Decorator: register a FLOP rule for the given op types. The rule
    receives a :class:`CostCtx` and returns the op's FLOP count."""

    def deco(fn):
        for t in op_types:
            if t in _COST_RULES:
                raise ValueError(f'cost rule for {t!r} registered twice')
            _COST_RULES[t] = fn
        return fn

    return deco


def has_cost_rule(op_type: str) -> bool:
    return op_type in _COST_RULES


def all_cost_rules():
    return dict(_COST_RULES)


class CostCtx:
    """What a cost rule may consult: input/output VarInfos resolved through
    the flow env (which plan.py keeps infer-bound as it walks), the op's
    attrs, and element-count helpers under the `assume_dim` substitution."""

    def __init__(self, op, env: Dict[str, VarInfo], block, assume_dim=1):
        self.op = op
        self.env = env
        self.block = block
        self.assume_dim = int(assume_dim)

    def info_of(self, name: str) -> VarInfo:
        if name in self.env:
            return self.env[name]
        if self.block is not None and self.block.has_var(name):
            return declared_info(self.block.var(name))
        return VarInfo()

    def input(self, slot: str) -> Optional[VarInfo]:
        names = self.op.inputs.get(slot, [])
        return self.info_of(names[0]) if names else None

    def inputs(self, slot: str) -> List[VarInfo]:
        return [self.info_of(n) for n in self.op.inputs.get(slot, [])]

    def output(self, slot: str = 'Out') -> Optional[VarInfo]:
        names = self.op.outputs.get(slot, [])
        return self.info_of(names[0]) if names else None

    def attr(self, name, default=None):
        return self.op.attrs.get(name, default)

    def elems(self, info: Optional[VarInfo]) -> int:
        return info_elems(info, self.assume_dim)

    def in_elems(self, slot: str) -> int:
        return self.elems(self.input(slot))

    def out_elems(self, slot: str = 'Out') -> int:
        names = self.op.outputs.get(slot, [])
        return sum(self.elems(self.info_of(n)) for n in names)

    def all_in_elems(self) -> int:
        return sum(self.elems(self.info_of(n))
                   for n in self.op.input_names())

    def all_out_elems(self) -> int:
        return sum(self.elems(self.info_of(n))
                   for n in self.op.output_names())


def op_flops(op, env: Dict[str, VarInfo], block, assume_dim=1) -> int:
    """FLOPs of one op under the current flow env (0 when no rule —
    plan.py reports the gap). plan.py calls this and prices bytes
    through its own per-name cache; :func:`op_cost` is the standalone
    API that computes both."""
    rule = _COST_RULES.get(op.type)
    if rule is None:
        return 0
    return max(int(rule(CostCtx(op, env, block, assume_dim))), 0)


def op_cost(op, env: Dict[str, VarInfo], block, assume_dim=1) -> OpCost:
    """Cost of one op under the current flow env. Bytes are always the
    generic Σ input/output VarInfo bytes; FLOPs come from the registered
    rule."""
    ctx = CostCtx(op, env, block, assume_dim)
    bytes_in = sum(info_nbytes(ctx.info_of(n), assume_dim)
                   for n in op.input_names())
    bytes_out = sum(info_nbytes(ctx.info_of(n), assume_dim)
                    for n in op.output_names())
    return OpCost(op_flops(op, env, block, assume_dim),
                  bytes_in, bytes_out)


# ---------------------------------------------------------------------------
# rules: elementwise / unary / comparisons
# ---------------------------------------------------------------------------

@cost_rule(*infer._ELTWISE_BINARY)
def _c_eltwise(ctx):
    return ctx.out_elems()


@cost_rule('fused_elemwise_add_activation')
def _c_fused_add_act(ctx):
    # one add + one activation per element; sigmoid/tanh transcendental
    f = 1 if ctx.attr('functor', 'relu') == 'relu' else TRANSCENDENTAL_FLOPS
    return (1 + f) * ctx.out_elems()


# transcendental members of the same-shape unary family
_TRANS_UNARY = frozenset((
    'exp', 'sqrt', 'rsqrt', 'cos', 'sin', 'acos', 'asin', 'cosh', 'sinh',
    'reciprocal', 'log', 'softplus', 'softsign', 'erf', 'logsigmoid',
    'atan', 'tanh_shrink', 'gelu', 'elu', 'selu', 'stanh', 'hard_swish',
    'swish', 'sigmoid', 'tanh', 'pow', 'l2_normalize'))


@cost_rule(*infer._SAME_SHAPE_UNARY, 'prelu')
def _c_unary(ctx):
    per = TRANSCENDENTAL_FLOPS if ctx.op.type in _TRANS_UNARY else 1
    return per * ctx.in_elems('x')


@cost_rule('softmax', 'log_softmax')
def _c_softmax(ctx):
    # exp + sum + div (+ log): priced as one transcendental pass + 2 linear
    return (TRANSCENDENTAL_FLOPS + 2) * ctx.in_elems('x')


@cost_rule('dropout')
def _c_dropout(ctx):
    return 2 * ctx.in_elems('x')        # mask draw + multiply


@cost_rule('cast', *infer._COMPARE)
def _c_per_elem(ctx):
    return ctx.out_elems()


@cost_rule('logical_not', 'isfinite', 'has_inf', 'has_nan')
def _c_bool_unary(ctx):
    return ctx.in_elems('x')


# ---------------------------------------------------------------------------
# rules: matmul family / reductions
# ---------------------------------------------------------------------------

def _dim(d, assume):
    return int(d) if known(d) else int(assume)


@cost_rule('matmul')
def _c_matmul(ctx):
    x, y = ctx.input('x'), ctx.input('y')
    k = None
    if x is not None and x.shape is not None and len(x.shape) >= 1:
        xs = list(x.shape)
        if ctx.attr('transpose_x', False) and len(xs) > 1:
            xs[-1], xs[-2] = xs[-2], xs[-1]
        k = _dim(xs[-1], ctx.assume_dim)
    elif y is not None and y.shape is not None and len(y.shape) >= 2:
        ys = list(y.shape)
        if ctx.attr('transpose_y', False):
            ys[-1], ys[-2] = ys[-2], ys[-1]
        k = _dim(ys[-2], ctx.assume_dim)
    return 2 * (k or 1) * ctx.out_elems()


@cost_rule('mul')
def _c_mul(ctx):
    x = ctx.input('x')
    xcd = ctx.attr('x_num_col_dims', 1)
    k = 1
    if x is not None and x.shape is not None:
        for d in x.shape[xcd:]:
            k *= _dim(d, ctx.assume_dim)
    return 2 * k * ctx.out_elems()


@cost_rule('dot')
def _c_dot(ctx):
    return 2 * ctx.in_elems('x')


@cost_rule(*infer._REDUCES, 'mean', 'cumsum')
def _c_reduce(ctx):
    return ctx.in_elems('x')


@cost_rule('logsumexp')
def _c_logsumexp(ctx):
    return (TRANSCENDENTAL_FLOPS + 1) * ctx.in_elems('x')


@cost_rule('sum')
def _c_sum_variadic(ctx):
    n = len(ctx.op.inputs.get('xs', []))
    return max(n - 1, 0) * ctx.out_elems()


# ---------------------------------------------------------------------------
# rules: data movement — 0 FLOPs, the generic byte accounting is the cost
# ---------------------------------------------------------------------------

_MOVE_OPS = ('reshape', 'transpose', 'squeeze', 'unsqueeze', 'concat',
             'split', 'stack', 'unstack', 'slice', 'flatten', 'flatten2',
             'expand', 'gather', 'one_hot', 'lookup_table', 'where', 'pad',
             'shape', 'fill_constant', 'fill_constant_batch_size_like',
             'fill_any_like', '__constant__', '__init__')


@cost_rule(*_MOVE_OPS)
def _c_move(ctx):
    return 0


@cost_rule('top_k', 'arg_max', 'arg_min')
def _c_select(ctx):
    return ctx.in_elems('x')            # one comparison sweep


# ---------------------------------------------------------------------------
# rules: nn
# ---------------------------------------------------------------------------

@cost_rule('conv2d')
def _c_conv2d(ctx):
    w = ctx.input('weight')
    if w is None or w.shape is None or len(w.shape) != 4:
        return 2 * ctx.out_elems()
    _oc, ic, kh, kw = (_dim(d, ctx.assume_dim) for d in w.shape)
    return 2 * ic * kh * kw * ctx.out_elems()


@cost_rule('pool2d')
def _c_pool2d(ctx):
    ks = ctx.attr('pool_size', 2)
    if ctx.attr('global_pooling', False) or ks in (-1, (-1, -1), [-1, -1]):
        return ctx.in_elems('x')
    ks = tuple(ks) if isinstance(ks, (list, tuple)) else (ks, ks)
    return int(ks[0]) * int(ks[1]) * ctx.out_elems()


@cost_rule('adaptive_pool2d')
def _c_adaptive_pool(ctx):
    return ctx.in_elems('x')


@cost_rule('batch_norm')
def _c_batch_norm(ctx):
    # stats (2 passes) + normalize (scale/shift/rsqrt) ≈ 10 flops/elem
    return 10 * ctx.in_elems('x')


@cost_rule('layer_norm', 'instance_norm', 'group_norm', 'lrn')
def _c_norm(ctx):
    return 10 * ctx.in_elems('x')


# ---------------------------------------------------------------------------
# rules: losses / metrics
# ---------------------------------------------------------------------------

@cost_rule('softmax_with_cross_entropy')
def _c_softmax_ce(ctx):
    return (TRANSCENDENTAL_FLOPS + 4) * ctx.in_elems('logits')


@cost_rule('cross_entropy')
def _c_cross_entropy(ctx):
    return (TRANSCENDENTAL_FLOPS + 1) * ctx.in_elems('x')


@cost_rule('square_error_cost')
def _c_square_error(ctx):
    return 3 * ctx.out_elems()


@cost_rule('sigmoid_cross_entropy_with_logits')
def _c_sigmoid_ce(ctx):
    return (TRANSCENDENTAL_FLOPS + 3) * ctx.in_elems('x')


@cost_rule('accuracy')
def _c_accuracy(ctx):
    return ctx.all_in_elems()


# ---------------------------------------------------------------------------
# rules: optimizer updates — factor × param elems (factor ≈ flops/elem of
# the update formula, from the kernel implementations in ops/optimizer_ops)
# ---------------------------------------------------------------------------

_OPT_FLOP_FACTORS = {
    'sgd': 2, 'momentum': 5, 'lars_momentum': 12, 'adam': 18, 'adamax': 12,
    'adagrad': 6, 'decayed_adagrad': 8, 'adadelta': 12, 'rmsprop': 12,
    'ftrl': 14, 'lamb': 24, 'dpsgd': 6, 'dgc_momentum': 10,
}


def _c_opt(ctx):
    factor = _OPT_FLOP_FACTORS.get(ctx.op.type, 8)
    return factor * ctx.in_elems('param')


for _t in infer._OPT_MIRROR:
    cost_rule(_t)(_c_opt)
if 'dgc_momentum' not in _COST_RULES:
    cost_rule('dgc_momentum')(_c_opt)


def _c_fused_opt(ctx):
    base = ctx.op.type[len('fused_'):]
    factor = _OPT_FLOP_FACTORS.get(base, 8)
    return factor * sum(ctx.elems(p) for p in ctx.inputs('params'))


for _t in infer._FUSED_OPT_MIRROR:
    cost_rule(_t)(_c_fused_opt)


def _c_sparse_opt(ctx):
    # rows-only scatter-apply: the update formula runs over the padded
    # COO vals (K × D), NOT the V × D table — that asymmetry vs the
    # dense family is the whole fast path (docs/SPARSE.md)
    base = ctx.op.type[len('sparse_'):]
    factor = _OPT_FLOP_FACTORS.get(base, 8)
    return factor * ctx.in_elems('vals')


for _t in infer._SPARSE_OPT_MIRROR:
    cost_rule(_t)(_c_sparse_opt)


# ---------------------------------------------------------------------------
# rules: collectives — local reduce math only; wire bytes are what the
# collective_* telemetry (PR 9) prices, not this model
# ---------------------------------------------------------------------------

@cost_rule('c_allreduce_sum', 'c_allreduce_max', 'c_allreduce_min',
           'c_allreduce_prod')
def _c_allreduce(ctx):
    return ctx.in_elems('x')


@cost_rule('c_allreduce_sum_bucket')
def _c_allreduce_bucket(ctx):
    return sum(ctx.elems(x) for x in ctx.inputs('xs'))


# ---------------------------------------------------------------------------
# rules: paged attention — the decode-pool read path. Bytes stay generic
# (Σ VarInfo nbytes), which is exactly the quantization story: an int8 pool
# prices its pages at 1 B/elem and its f32 row scales at 4 B/row with no
# op-specific bytes code here. FLOPs walk the padded context.
# ---------------------------------------------------------------------------

def _pdim(info, i, assume):
    """Dim `i` of a VarInfo, with unknown rank/dim priced at `assume`."""
    if info is None or info.shape is None or i >= len(info.shape):
        return int(assume)
    return int(info.shape[i]) if known(info.shape[i]) else int(assume)


@cost_rule('paged_attention', 'paged_prefill_attention')
def _c_paged_attention(ctx):
    # per query row against T_pad = num_blocks_per_seq × block_size keys:
    # QK^T (2D) + softmax (~TRANS+2) + PV (2D) — the padded extent is the
    # honest decode cost; masked positions still burn the lanes
    kp = ctx.input('k_pages')
    bt = ctx.input('block_tables')
    a = ctx.assume_dim
    heads = _pdim(kp, 0, a)
    block_size = _pdim(kp, 2, a)
    head_dim = _pdim(kp, 3, a)
    seqs = _pdim(bt, 0, a)
    t_pad = _pdim(bt, 1, a) * block_size
    queries = max(1, ctx.out_elems() // max(1, head_dim))
    flops = queries * t_pad * (4 * head_dim + TRANSCENDENTAL_FLOPS + 2)
    if ctx.input('k_scales') is not None:
        # int8 pool: one dequant multiply per gathered K and V element
        # (the gather materializes every sequence's padded window once,
        # shared across that sequence's query rows)
        flops += 2 * seqs * heads * t_pad * head_dim
    return flops


# ---------------------------------------------------------------------------
# fallback coverage: every remaining op type with an INFER rule gets a
# bytes-only cost rule so the registries stay coverage-aligned (the tier-1
# coverage test asserts infer rules ⊆ cost rules); genuinely-unknown op
# types stay unregistered and plan.py reports them as gaps.
# ---------------------------------------------------------------------------

def _c_bytes_only(ctx):
    return 0


for _t in infer.all_rules():
    if _t not in _COST_RULES:
        cost_rule(_t)(_c_bytes_only)
