"""Staged-program extension of the memory planner: pipeline stage costs,
schedule-aware peak-HBM, auto stage-cut, and microbatch-count solves.

``plan_program`` (plan.py) prices a Program as ONE device's step. A
pipelined Program is p stages × m microbatches with a *schedule* deciding
how many microbatches' residuals are in flight at once — that residency,
not the kernels, separates GPipe from 1F1B. This module re-derives the
staged view from the same zero-trace walk:

- ``plan_staged_program`` splits the forward at the cut vars and reports
  per-stage FLOPs / bytes / parameter state / activation residuals, then
  charges each stage ``in_flight(schedule, stage)`` microbatches of
  residuals: GPipe holds all ``m`` (every forward runs before any
  backward), 1F1B holds ``min(m, p - stage)`` (warm-up depth — the last
  stage holds one), interleaved holds ``min(m, p)`` (p in flight over
  finer virtual chunks). ``host_peak_bytes`` is the single-program view —
  what the executor's scan lowering actually keeps live on a host where
  all stages share one device — and is the number to compare against
  ``jit(...).compile().memory_analysis()``.
- ``solve_stage_cuts`` is the auto-cut: candidates are the same
  single-output forward boundaries ``select_checkpoints`` uses, and a DP
  picks the p−1 cuts minimizing the max per-stage predicted cost
  (FLOPs + bytes) — balance computed, not hand-tuned.
- ``solve_microbatches`` picks the smallest microbatch count whose
  predicted staged peak fits ``PADDLE_TPU_HBM_BUDGET_MB``, the same way
  ``auto_remat`` consumes the plan. GPipe's peak is flat in m (m × act/m
  is constant — the reason 1F1B exists), so under GPipe the solve returns
  the stage count and reports the shortfall honestly.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..framework import BACKWARD_OP_TYPE
from .checks import _op_external_reads
from .plan import plan_program

__all__ = ['StagedPlan', 'StageReport', 'plan_staged_program',
           'solve_stage_cuts', 'solve_microbatches', 'schedule_in_flight',
           'stage_cut_candidates', 'wave_size']

# the schedule set (mirrored by partition.pipeline.PP_SCHEDULES — kept
# literal here so analysis stays importable without the partition layer)
SCHEDULES = ('gpipe', '1f1b', 'interleaved')


def wave_size(schedule, num_stages, num_microbatches):
    """Microbatches whose residuals one backward wave keeps in flight on
    the single-program (host/scan) lowering: GPipe backpropagates after
    all m forwards, 1F1B after each one, interleaved after each wave of
    ≤ num_stages (the largest divisor of m, so waves tile the batch)."""
    m = int(num_microbatches)
    if schedule == 'gpipe':
        return m
    if schedule == '1f1b':
        return 1
    if schedule == 'interleaved':
        p = max(1, int(num_stages))
        return max(w for w in range(1, min(p, m) + 1) if m % w == 0)
    raise ValueError(
        f"unknown pipeline schedule {schedule!r} "
        f"(supported: {', '.join(SCHEDULES)})")


def schedule_in_flight(schedule, stage_idx, num_stages, num_microbatches):
    """In-flight microbatches at `stage_idx` in the DISTRIBUTED view (one
    stage per device): GPipe m everywhere; 1F1B p−i at stage i (stage 0
    admits the whole warm-up, the last stage drains immediately);
    interleaved ≤ p in flight across its virtual chunks."""
    m, p = int(num_microbatches), int(num_stages)
    if schedule == 'gpipe':
        return m
    if schedule == '1f1b':
        return min(m, p - int(stage_idx))
    if schedule == 'interleaved':
        return min(m, p)
    raise ValueError(
        f"unknown pipeline schedule {schedule!r} "
        f"(supported: {', '.join(SCHEDULES)})")


class StageReport:
    """One pipeline stage's predicted cost/residency."""

    __slots__ = ('index', 'n_ops', 'flops', 'bytes', 'param_bytes',
                 'act_bytes', 'act_bytes_per_mb', 'in_flight',
                 'peak_bytes')

    def __init__(self, **kw):
        for k in self.__slots__:
            setattr(self, k, int(kw.get(k, 0)))

    def to_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}


class StagedPlan:
    """Per-stage breakdown + schedule-charged peaks for one cut/m pair."""

    def __init__(self, schedule, num_microbatches, cut_vars, stages,
                 base_plan):
        self.schedule = schedule
        self.num_microbatches = int(num_microbatches)
        self.cut_vars = list(cut_vars)
        self.stages: List[StageReport] = stages
        self.base = base_plan
        m = max(1, self.num_microbatches)
        w = wave_size(schedule, len(stages), m)
        act = base_plan.activation_bytes
        # single-program view: state/feeds/grads unchanged, residuals
        # scale to the in-flight wave (GPipe w=m keeps this the unstaged
        # peak — bit-for-bit the plan_program number)
        self.host_in_flight = w
        self.host_peak_bytes = (base_plan.peak_bytes - act
                                + (act // m) * w)

    @property
    def num_stages(self):
        return len(self.stages)

    @property
    def max_stage_peak_bytes(self):
        return max((s.peak_bytes for s in self.stages), default=0)

    @property
    def max_stage_flops(self):
        return max((s.flops for s in self.stages), default=0)

    @property
    def balance(self):
        """max/mean per-stage cost — 1.0 is a perfectly balanced cut."""
        costs = [s.flops + s.bytes for s in self.stages]
        mean = sum(costs) / max(1, len(costs))
        return (max(costs) / mean) if mean else 1.0

    def to_dict(self):
        return {
            'schedule': self.schedule,
            'num_microbatches': self.num_microbatches,
            'num_stages': self.num_stages,
            'cut_vars': list(self.cut_vars),
            'host_in_flight': self.host_in_flight,
            'host_peak_bytes': self.host_peak_bytes,
            'max_stage_peak_bytes': self.max_stage_peak_bytes,
            'balance': round(self.balance, 4),
            'stages': [s.to_dict() for s in self.stages],
        }

    def format_report(self, budget_bytes=None):
        mib = float(1 << 20)
        lines = [f'# Staged plan: {self.num_stages} stage(s), '
                 f'schedule={self.schedule}, m={self.num_microbatches}']
        verdict = ''
        if budget_bytes:
            fits = self.host_peak_bytes <= budget_bytes
            verdict = (f"  [{'FITS' if fits else 'EXCEEDS'} budget "
                       f"{budget_bytes / mib:.1f} MiB]")
        lines.append(f'host peak (scan lowering): '
                     f'{self.host_peak_bytes / mib:.3f} MiB '
                     f'({self.host_in_flight} microbatch(es) of residuals '
                     f'in flight){verdict}')
        lines.append(f'balance (max/mean stage cost): {self.balance:.3f}')
        lines.append('stage   ops        flops      bytes(MiB)  '
                     'params(MiB)  act/mb(MiB)  in-flight  peak(MiB)')
        for s in self.stages:
            lines.append(
                f'  {s.index:<4}  {s.n_ops:<4} {s.flops:>12,}  '
                f'{s.bytes / mib:>10.3f}  {s.param_bytes / mib:>11.3f}  '
                f'{s.act_bytes_per_mb / mib:>11.3f}  {s.in_flight:>9}  '
                f'{s.peak_bytes / mib:>9.3f}')
        return lines


def _forward_split(program):
    """(ops, fwd_ops, marker) of the global block; marker None when the
    program has no backward."""
    ops = list(program.global_block().ops)
    bwd_idx = next((i for i, op in enumerate(ops)
                    if op.type == BACKWARD_OP_TYPE), None)
    if bwd_idx is None:
        return ops, ops, None
    return ops, ops[:bwd_idx], ops[bwd_idx]


def _stage_bounds(fwd_ops, cut_vars):
    """[(lo, hi)] per stage — the loss tail after the last cut joins the
    final stage for accounting (the executor runs it on the reassembled
    batch either way). Raises naming any cut no forward op produces or
    any out-of-order cut."""
    producer: Dict[str, int] = {}
    for i, op in enumerate(fwd_ops):
        for n in op.output_names():
            producer.setdefault(n, i)
    bounds = []
    for c in cut_vars:
        if c not in producer:
            raise ValueError(
                f'pipeline cut var {c!r} is not produced by any forward '
                f'op — cuts must name forward activations')
        bounds.append(producer[c] + 1)
    if bounds != sorted(bounds) or len(set(bounds)) != len(bounds):
        raise ValueError(
            f'pipeline cut vars {list(cut_vars)!r} are not in forward '
            f'order (producer boundaries {bounds})')
    stages, prev = [], 0
    for b in bounds:
        stages.append((prev, b))
        prev = b
    stages.append((prev, len(fwd_ops)))
    return stages


def plan_staged_program(program, cut_vars, num_microbatches,
                        schedule='gpipe', fetch_names=(), feed_names=(),
                        feed_shapes=None, donate=True, assume_dim=1):
    """Build the :class:`StagedPlan` for `program` split at `cut_vars`.

    Per-stage bytes come straight from the plan's per-op cost walk;
    activation residuals are attributed to the stage whose op produced
    them (the ``out_bytes`` term of the backward model), scaled to one
    microbatch and multiplied by the schedule's in-flight count."""
    if schedule not in SCHEDULES:
        raise ValueError(
            f"unknown pipeline schedule {schedule!r} "
            f"(supported: {', '.join(SCHEDULES)})")
    m = int(num_microbatches)
    if m <= 0:
        raise ValueError(f'num_microbatches must be > 0, got {m}')
    base = plan_program(program, fetch_names=fetch_names,
                        feed_names=feed_names, feed_shapes=feed_shapes,
                        donate=donate, assume_dim=assume_dim,
                        checkpoints=[])
    if not hasattr(base, '_bwd_model'):
        raise ValueError(
            'plan_staged_program: program has no backward marker — '
            'pipeline schedules stage a training step')
    _, fwd_ops, marker = _forward_split(program)
    bounds = _stage_bounds(fwd_ops, cut_vars)
    out_bytes, _, _, _ = base._bwd_model

    persist = {v.name for v in program.list_vars() if v.persistable}
    blk = program.global_block()
    has_grad = set(marker.attrs.get('params', []))
    cost_by_idx = {i: c for i, _t, c, _s in base.op_costs}

    from .cost import info_nbytes
    from .infer import declared_info

    def var_nbytes(name):
        return (info_nbytes(declared_info(blk.var(name)), assume_dim)
                if blk.has_var(name) else 0)

    stages = []
    p = len(bounds)
    for si, (lo, hi) in enumerate(bounds):
        flops = sum(cost_by_idx[i].flops for i in range(lo, hi)
                    if i in cost_by_idx)
        nbytes = sum(cost_by_idx[i].bytes for i in range(lo, hi)
                     if i in cost_by_idx)
        sparams = []
        for op in fwd_ops[lo:hi]:
            for n in op.input_names():
                if n in persist and n not in sparams:
                    sparams.append(n)
        param_bytes = sum(var_nbytes(n) for n in sparams)
        # stage state = params (1×) + their gradient buffers (grads
        # mirror their parameter's shape — plan.py's backward model)
        param_bytes += sum(var_nbytes(n) for n in sparams
                           if n in has_grad)
        act = sum(out_bytes[lo:hi])
        act_mb = act // m
        in_flight = schedule_in_flight(schedule, si, p, m)
        stages.append(StageReport(
            index=si, n_ops=hi - lo, flops=flops, bytes=nbytes,
            param_bytes=param_bytes, act_bytes=act,
            act_bytes_per_mb=act_mb, in_flight=in_flight,
            peak_bytes=param_bytes + in_flight * act_mb))
    return StagedPlan(schedule, m, cut_vars, stages, base)


def stage_cut_candidates(program, fetch_names=(), feed_names=(),
                         feed_shapes=None, assume_dim=1):
    """Every cuttable forward boundary, in program order: the names of
    single-non-persistable-output activations later ops read — the same
    candidate set ``solve_stage_cuts`` optimizes over, exposed so manual
    cuts can be enumerated against the auto-cut (tools/bench_pp.py)."""
    base = plan_program(program, fetch_names=fetch_names,
                        feed_names=feed_names, feed_shapes=feed_shapes,
                        assume_dim=assume_dim, checkpoints=[])
    if not hasattr(base, '_bwd_model'):
        raise ValueError(
            'stage_cut_candidates: program has no backward marker')
    _, fwd_ops, _ = _forward_split(program)
    _, _, _, last = base._bwd_model
    persist = {v.name for v in program.list_vars() if v.persistable}
    out = []
    for i, op in enumerate(fwd_ops):
        outs = [v for v in op.output_names() if v not in persist]
        if len(outs) == 1 and last.get(outs[0], i) > i:
            out.append(outs[0])
    return out


def solve_stage_cuts(program, num_stages, fetch_names=(), feed_names=(),
                     feed_shapes=None, assume_dim=1):
    """Auto-cut: pick num_stages−1 cut vars balancing predicted per-stage
    cost (FLOPs + bytes). Returns ``(cut_var_names, report)`` where the
    report carries the per-stage costs of the chosen cut.

    Candidates are forward ops with exactly ONE non-persistable output
    that later ops read — the boundaries the executor can split at (the
    same candidate set as auto-remat, so every solvable cut is also a
    lowerable one). A DP over those boundaries minimizes the maximum
    stage cost; with fewer candidates than stages it raises rather than
    return a degenerate cut."""
    p = int(num_stages)
    if p < 2:
        raise ValueError(f'num_stages must be >= 2, got {num_stages}')
    base = plan_program(program, fetch_names=fetch_names,
                        feed_names=feed_names, feed_shapes=feed_shapes,
                        assume_dim=assume_dim, checkpoints=[])
    if not hasattr(base, '_bwd_model'):
        raise ValueError(
            'solve_stage_cuts: program has no backward marker')
    _, fwd_ops, _ = _forward_split(program)
    _, _, _, last = base._bwd_model
    persist = {v.name for v in program.list_vars() if v.persistable}
    cost_by_idx = {i: c for i, _t, c, _s in base.op_costs}
    n = len(fwd_ops)
    op_cost = [cost_by_idx[i].flops + cost_by_idx[i].bytes
               if i in cost_by_idx else 0 for i in range(n)]
    prefix = [0] * (n + 1)
    for i in range(n):
        prefix[i + 1] = prefix[i] + op_cost[i]

    # boundary b (split before op b) ← single-output op b-1 read later
    boundary_var = {}
    for i, op in enumerate(fwd_ops):
        outs = [v for v in op.output_names() if v not in persist]
        if len(outs) != 1:
            continue
        if last.get(outs[0], i) > i:
            boundary_var[i + 1] = outs[0]
    cands = sorted(boundary_var)
    if len(cands) < p - 1:
        raise ValueError(
            f'solve_stage_cuts: only {len(cands)} cuttable boundaries for '
            f'{p} stages — the forward has too few single-output '
            f'activations to cut')

    def seg(a, b):
        return prefix[b] - prefix[a]

    # dp[k][j]: min over first k segments ending at boundary cands[j] of
    # the max segment cost; reconstruct via choice[]
    INF = float('inf')
    ncand = len(cands)
    dp = [[INF] * ncand for _ in range(p - 1)]
    choice = [[-1] * ncand for _ in range(p - 1)]
    for j, b in enumerate(cands):
        dp[0][j] = seg(0, b)
    for k in range(1, p - 1):
        for j, b in enumerate(cands):
            for jp in range(j):
                prev = dp[k - 1][jp]
                if prev == INF:
                    continue
                cur = max(prev, seg(cands[jp], b))
                if cur < dp[k][j]:
                    dp[k][j] = cur
                    choice[k][j] = jp
    best, best_j = INF, -1
    for j, b in enumerate(cands):
        if dp[p - 2][j] == INF:
            continue
        total = max(dp[p - 2][j], seg(b, n))
        if total < best:
            best, best_j = total, j
    if best_j < 0:
        raise ValueError('solve_stage_cuts: no feasible cut found')
    picks, k, j = [], p - 2, best_j
    while k >= 0:
        picks.append(cands[j])
        j = choice[k][j]
        k -= 1
    picks.reverse()
    cuts = [boundary_var[b] for b in picks]
    seg_costs = []
    prev = 0
    for b in picks + [n]:
        seg_costs.append(seg(prev, b))
        prev = b
    mean = sum(seg_costs) / len(seg_costs)
    return cuts, {
        'cut_vars': cuts,
        'num_stages': p,
        'stage_costs': seg_costs,
        'max_stage_cost': max(seg_costs),
        'balance': (max(seg_costs) / mean) if mean else 1.0,
        'candidates': len(cands),
    }


def solve_microbatches(program, cut_vars, schedule, budget_bytes,
                       fetch_names=(), feed_names=(), feed_shapes=None,
                       assume_dim=1, max_microbatches=64):
    """Smallest microbatch count whose predicted staged host peak fits
    `budget_bytes` (the ``PADDLE_TPU_HBM_BUDGET_MB`` consumption path).
    Returns ``(m, predicted_peak_bytes, fits)``.

    More microbatches shrink 1F1B/interleaved residency (w × act/m) but
    leave GPipe flat (m × act/m) — under GPipe the solve returns the
    stage count (the schedule's natural minimum) with ``fits`` reporting
    whether even that is under budget. Candidates are capped at
    `max_microbatches`; runtime batch divisibility is enforced by the
    executor, not here."""
    nstages = len(cut_vars) + 1
    if schedule == 'gpipe':
        plan = plan_staged_program(program, cut_vars, nstages, schedule,
                                   fetch_names=fetch_names,
                                   feed_names=feed_names,
                                   feed_shapes=feed_shapes,
                                   assume_dim=assume_dim)
        return nstages, plan.host_peak_bytes, \
            plan.host_peak_bytes <= budget_bytes
    best_m, best_peak = None, None
    m = nstages
    while m <= max_microbatches:
        plan = plan_staged_program(program, cut_vars, m, schedule,
                                   fetch_names=fetch_names,
                                   feed_names=feed_names,
                                   feed_shapes=feed_shapes,
                                   assume_dim=assume_dim)
        peak = plan.host_peak_bytes
        if best_peak is None or peak < best_peak:
            best_m, best_peak = m, peak
        if peak <= budget_bytes:
            return m, peak, True
        m *= 2
    return best_m, best_peak, False
