"""Structured diagnostics for the static Program verifier.

A :class:`Diagnostic` is one finding about one op (or var) of a Program:
a severity, a stable machine-readable code (docs/ANALYSIS.md catalogs
them), a human message, and — when construction-site capture is on
(``PADDLE_TPU_VERIFY`` ≠ ``off``, see framework.Operator) — the
``file:line`` of the Python call that appended the op, so a verifier
finding points back at the model code that built the bad op instead of
at an opaque trace failure three layers down.

Severity policy (docs/ANALYSIS.md):

- ``error`` — the program cannot lower correctly (dangling reads,
  impossible shapes, malformed attrs). ``verify_program`` callers raise
  :class:`ProgramVerificationError` on these.
- ``warning`` — lowering will work but something is suspicious or
  costs performance (a fetched persistable blocks donation, drifting
  comm dtypes). Never raised on; tier-1 recipes must stay free of them.
- ``info`` — coverage notes (op without an inference rule, dead writes
  the DCE pass will sweep). Reported by tools/lint_program.py only.
"""
from __future__ import annotations

from typing import List, Optional

__all__ = ['Diagnostic', 'ProgramVerificationError', 'SEVERITIES',
           'max_severity', 'format_report', 'severity_at_least']

# ascending order; index = rank
SEVERITIES = ('info', 'warning', 'error')


def _rank(severity: str) -> int:
    try:
        return SEVERITIES.index(severity)
    except ValueError:
        raise ValueError(f'unknown severity {severity!r}; '
                         f'expected one of {SEVERITIES}')


class Diagnostic:
    """One verifier finding, anchored to an op of the analyzed Program."""

    __slots__ = ('severity', 'code', 'message', 'op_type', 'op_index',
                 'block_idx', 'var', 'site', 'stage')

    def __init__(self, severity: str, code: str, message: str,
                 op_type: Optional[str] = None, op_index: Optional[int] = None,
                 block_idx: int = 0, var: Optional[str] = None,
                 site: Optional[str] = None, stage: Optional[str] = None):
        _rank(severity)            # validate eagerly
        self.severity = severity
        self.code = code
        self.message = message
        self.op_type = op_type
        self.op_index = op_index
        self.block_idx = block_idx
        self.var = var
        self.site = site
        self.stage = stage

    def key(self):
        """Identity used to diff diagnostics across pass rewrites (op
        indices shift when passes remove ops, so position is excluded)."""
        return (self.code, self.severity, self.op_type, self.var)

    def to_dict(self):
        return {k: getattr(self, k) for k in self.__slots__
                if getattr(self, k) is not None}

    def format(self) -> str:
        where = ''
        if self.op_type is not None:
            pos = f'#{self.op_index}' if self.op_index is not None else ''
            blk = f'/b{self.block_idx}' if self.block_idx else ''
            where = f' [{self.op_type}{pos}{blk}]'
        var = f' var={self.var!r}' if self.var else ''
        site = f' (built at {self.site})' if self.site else ''
        return f'{self.severity}:{self.code}{where}{var}: ' \
               f'{self.message}{site}'

    def __repr__(self):
        return f'Diagnostic({self.format()})'


def severity_at_least(diags: List[Diagnostic], severity: str):
    """Subset of `diags` at or above `severity`."""
    floor = _rank(severity)
    return [d for d in diags if _rank(d.severity) >= floor]


def max_severity(diags: List[Diagnostic]) -> Optional[str]:
    if not diags:
        return None
    return SEVERITIES[max(_rank(d.severity) for d in diags)]


def format_report(diags: List[Diagnostic], header: str = '') -> str:
    lines = [header] if header else []
    by_sev = {s: [d for d in diags if d.severity == s]
              for s in reversed(SEVERITIES)}
    for sev, ds in by_sev.items():
        for d in ds:
            lines.append('  ' + d.format())
    counts = ', '.join(f'{len(ds)} {sev}' for sev, ds in by_sev.items()
                       if ds)
    lines.append(f'  -- {counts or "clean"}')
    return '\n'.join(lines)


class ProgramVerificationError(RuntimeError):
    """A Program failed static verification. Carries the error-severity
    diagnostics; `pass_name` is set when the failure is an IR pass
    post-condition (the pass emitted an inconsistent program)."""

    def __init__(self, diagnostics: List[Diagnostic], stage: str = 'verify',
                 pass_name: Optional[str] = None):
        self.diagnostics = list(diagnostics)
        self.stage = stage
        self.pass_name = pass_name
        origin = (f"IR pass '{pass_name}' emitted an inconsistent program"
                  if pass_name else f'program verification failed ({stage})')
        super().__init__(format_report(self.diagnostics, origin + ':'))
