"""Static Program verifier: shape/dtype inference + dataflow diagnostics.

Runs BEFORE lowering and BETWEEN IR passes, with zero tracing — a
malformed program fails here with the op and its Python construction
site, not three layers later inside an XLA trace error.

Layers:

- :mod:`infer` — per-op ``VarInfo(shape, dtype, lod_level)`` inference
  (``infer_rule`` registry, UNKNOWN-dim lattice);
- :mod:`checks` — the diagnostic suite (read-before-write, dead code,
  shape/dtype mismatch, collective consistency, donation hazards, RNG
  salt lint);
- :mod:`cost` / :mod:`plan` — the per-op FLOP/byte cost model
  (``cost_rule`` registry, same coverage contract) and the
  whole-Program peak-HBM planner feeding ``tools/plan_program.py``,
  the ``auto_remat`` IR pass (``PADDLE_TPU_HBM_BUDGET_MB``), and
  ``PADDLE_TPU_ALLREDUCE_BUCKET_MB=auto`` (docs/ANALYSIS.md "Cost
  model & memory planner");
- :func:`verify_program` — one call returning the diagnostics;
- :func:`assert_verified` — raise :class:`ProgramVerificationError` on
  error-severity findings.

``PADDLE_TPU_VERIFY`` ∈ {``off``, ``passes``, ``full``} (default
``off``):

- ``off``    — nothing runs, construction-site capture disabled;
- ``passes`` — every IR pass output is re-verified at the pass boundary
  (ir/pass_base.PassManager); a pass emitting an inconsistent program
  raises naming the pass;
- ``full``   — ``passes`` plus an Executor pre-lowering validation of
  the user program on every compile-cache miss.

All verification is program-BUILD-time work (it runs on compile-cache
misses, never per step); tools/bench_verify.py prices it (<2% on the
bench recipe, PERF.md §17). ``tools/lint_program.py`` runs the same
checks from the command line over saved inference models or recipe
builders.
"""
from __future__ import annotations

import os

from .diagnostics import (Diagnostic, ProgramVerificationError,  # noqa: F401
                          SEVERITIES, format_report, max_severity,
                          severity_at_least)
from .infer import (UNKNOWN, VarInfo, InferError, infer_rule,  # noqa: F401
                    has_rule, all_rules)
from .cost import (OpCost, cost_rule, has_cost_rule,  # noqa: F401
                   all_cost_rules, op_cost)
from .plan import (MemoryPlan, plan_program,  # noqa: F401
                   select_checkpoints, gradient_bytes)
from .checks import run_checks

__all__ = ['Diagnostic', 'ProgramVerificationError', 'SEVERITIES',
           'VarInfo', 'UNKNOWN', 'InferError', 'infer_rule', 'has_rule',
           'all_rules', 'verify_program', 'assert_verified', 'verify_level',
           'format_report', 'max_severity', 'severity_at_least',
           'VERIFY_ENV', 'VERIFY_LEVELS',
           'OpCost', 'cost_rule', 'has_cost_rule', 'all_cost_rules',
           'op_cost', 'MemoryPlan', 'plan_program', 'select_checkpoints',
           'gradient_bytes']

VERIFY_ENV = 'PADDLE_TPU_VERIFY'
VERIFY_LEVELS = ('off', 'passes', 'full')


def verify_level() -> str:
    """Current ``PADDLE_TPU_VERIFY`` level; unknown values raise listing
    the choices (strict parse, same contract as the other env knobs)."""
    raw = os.environ.get(VERIFY_ENV)
    if raw is None or raw == '':
        return 'off'
    lvl = raw.strip().lower()
    if lvl not in VERIFY_LEVELS:
        raise ValueError(
            f'{VERIFY_ENV}={raw!r} invalid; expected one of '
            f'{list(VERIFY_LEVELS)}')
    return lvl


def capture_sites() -> bool:
    """Whether framework.Operator records construction sites (off at
    level 'off' — the per-op stack walk is program-build-time-cheap but
    not free)."""
    return verify_level() != 'off'


def verify_program(program, fetch_names=(), feed_names=(), stage='pre'):
    """Statically verify `program`; returns the list of Diagnostics
    (never raises on findings — see :func:`assert_verified`)."""
    return run_checks(program, fetch_names=fetch_names,
                      feed_names=feed_names, stage=stage)


def assert_verified(program, fetch_names=(), feed_names=(), stage='pre',
                    pass_name=None, baseline=None):
    """Verify and RAISE :class:`ProgramVerificationError` on
    error-severity diagnostics. With `baseline` (a set of Diagnostic
    keys), only NEW errors raise — the pass post-condition contract: a
    pass must not introduce inconsistencies, but is not blamed for ones
    already present in its input. Returns the full diagnostic list."""
    diags = verify_program(program, fetch_names=fetch_names,
                           feed_names=feed_names, stage=stage)
    errors = severity_at_least(diags, 'error')
    if baseline is not None:
        errors = [d for d in errors if d.key() not in baseline]
    if errors:
        raise ProgramVerificationError(errors, stage=stage,
                                       pass_name=pass_name)
    return diags
