"""Multi-replica router: least-loaded dispatch over N engine replicas with
circuit-breaker awareness, cold-replica gating, mid-stream failover, and
rolling restarts behind drain (docs/SERVING.md "Serving tier").

One router process fronts N independent replica processes (each a
``ServingServer`` with a decode scheduler — ``python -m
paddle_tpu.serving.tier.replica`` is the canonical one). The router holds
NO model state: it reads each replica's always-on ``/healthz`` (status,
breaker states, decode load, and the PR-13 ``warmup`` field) on a poll
loop, and dispatches each ``/generate`` to the lowest-loaded routable
replica.

Routability ladder (per replica):

- ``draining`` (router-side, rolling restart) → never routed;
- ``/healthz`` 503 ``degraded`` (circuit breaker open) → drained, EXCEPT a
  breaker reporting ``half_open``: the router routes exactly ONE in-flight
  request there as the probe — success closes the replica's breaker and
  re-admits it (the breaker can only heal if someone feeds it a probe);
- ``warmup.done`` false → not routed (a restarted replica never serves its
  first requests into the compile cliff);
- otherwise routable; ties broken by load = router-side in-flight + the
  replica's reported ``active + waiting``.

Failover contract (the zero-drop rule): a dispatch that fails BEFORE the
first generation event — connection refused, replica died pre-stream, 500,
503 — is transparently retried on the next-best replica (generation is
deterministic greedy, so a retry is idempotent). Once a token has been
forwarded, a replica death surfaces as an error event on that stream: a
dying replica kills only its in-flight streams; everything queued or new
reroutes with zero drops (subprocess kill -9 tested,
tests/framework/test_router_failover.py).

Observability (docs/OBSERVABILITY.md): the router is the trace EDGE —
``maybe_sample()`` decides once per request, the context rides the
``X-PaddleTPU-Trace`` header to the replica, and the router records the
request root / per-attempt dispatch / retry spans around the replica's
spans. ``GET /metrics/fleet`` serves the replicas' merged Prometheus
export (counter-sum / gauge-label / bucket-merge), and the health poll
doubles as the clock handshake trace_merge.py aligns timelines with.

Strict-parse knobs (tier/knobs.py): ``PADDLE_TPU_ROUTER_REPLICAS``,
``PADDLE_TPU_ROUTER_PORT``, ``PADDLE_TPU_ROUTER_HEALTH_POLL_S``; plus
``PADDLE_TPU_TRACE_SAMPLE`` / ``PADDLE_TPU_TRACE_DIR``
(observability/trace_context.py).
"""
from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import metrics as _m
from ..errors import InvalidRequest, NoReplicaAvailable
from ...log_helper import get_logger
from ...observability import distributed as _dobs
from ...observability.trace_context import maybe_sample
from .knobs import (ENV_ROUTER_HEALTH_POLL_S, ENV_ROUTER_PORT,
                    ENV_ROUTER_REPLICAS, parse_float_env, parse_int_env,
                    parse_replicas_env)

__all__ = ['Router', 'RouterServer', 'RoutedGeneration', 'Replica']

# /generate schema mirrored from serving/server.py: the router rejects
# unknown keys with the same 400 so a typo fails at the FRONT door instead
# of after a replica round-trip
_SAMPLING_KEYS = ('temperature', 'top_k', 'top_p', 'seed')
_GENERATE_KEYS = frozenset(('prompt', 'max_new_tokens', 'eos_id', 'stream',
                            'timeout_ms', 'request_id', *_SAMPLING_KEYS))


def _attach_sampling(payload, temperature, top_k, top_p, seed, request_id):
    """Add per-request sampling keys to a /generate payload. A SAMPLED
    request with no pinned identity gets a router-stamped ``request_id``:
    the id seeds the stream (serving/decode/sampling.py), so a pre-stream
    failover retry on another replica REPLAYS the same tokens — the
    determinism that makes zero-drop rerouting idempotent extends from
    greedy to sampled traffic."""
    if temperature is not None:
        payload['temperature'] = float(temperature)
    if top_k is not None:
        payload['top_k'] = int(top_k)
    if top_p is not None:
        payload['top_p'] = float(top_p)
    if seed is not None:
        payload['seed'] = int(seed)
    if request_id is not None:
        payload['request_id'] = str(request_id)
    elif payload.get('temperature') and seed is None:
        payload['request_id'] = uuid.uuid4().hex[:16]
    return payload

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [router] %(message)s')


def _span(ctx, name, start_perf, end_perf, **args):
    """Router-side span record; free (one None check) when untraced."""
    if ctx is None:
        return
    _m.trace_spans_recorded.inc()
    _dobs.record_span(ctx, name, start_perf, end_perf, **args)

#: dispatch failures that are the REPLICA's fault → retry elsewhere.
#: 4xx (bad request, overload backpressure, deadline) are the CLIENT's
#: contract with the tier and propagate unchanged.
_REROUTE_HTTP_CODES = (500, 503)


class Replica:
    """Router-side view of one replica process."""

    def __init__(self, url):
        self.url = url.rstrip('/')
        self.healthy = False
        self.warmed = False
        self.half_open = False
        self.draining = False         # router-side (rolling restart)
        self.reported_load = 0        # decode active + waiting at last poll
        self.inflight = 0             # router-side, updated at dispatch
        self.last_poll_ok = 0.0
        # clock handshake (docs/OBSERVABILITY.md): estimated replica-unix
        # minus router-unix, from the health poll's RTT midpoint — what
        # trace_merge.py uses to align this replica's spans
        self.clock_offset = None
        self.replica_id = None        # reported by /healthz when available
        # cached /healthz windowed-series snapshots (queue_depth /
        # occupancy / ttft) — the autoscaler's decision inputs
        self.series = {}
        self._lock = threading.Lock()

    def load(self):
        return self.inflight + self.reported_load

    def routable(self):
        if self.draining:
            return False
        if self.healthy and self.warmed:
            return True
        # half-open probe: one request at a time re-admits a tripped replica
        return self.half_open and self.inflight == 0

    def begin(self):
        with self._lock:
            self.inflight += 1
            _m.router_replica_inflight.labels(replica=self.url).set(
                self.inflight)

    def end(self):
        with self._lock:
            self.inflight = max(self.inflight - 1, 0)
            _m.router_replica_inflight.labels(replica=self.url).set(
                self.inflight)

    def mark_dead(self):
        self.healthy = False
        self.half_open = False

    def state(self):
        return {'url': self.url, 'healthy': self.healthy,
                'warmed': self.warmed, 'half_open': self.half_open,
                'draining': self.draining, 'inflight': self.inflight,
                'reported_load': self.reported_load}


class RoutedGeneration:
    """One routed streaming generation: ``events()`` yields the replica's
    NDJSON events (``{'token','index'}`` lines, then the ``done`` line with
    routing metadata added). ``replica``/``retries`` describe the dispatch
    that is actually streaming."""

    def __init__(self, router, payload, timeout):
        self._router = router
        self._payload = payload
        self._timeout = timeout
        self.replica = None           # url actually streaming
        self.retries = 0              # reroutes before streaming began
        self.first_event_at = None
        # sampling is decided ONCE here at the edge; the context travels
        # with every dispatch so a trace is complete or absent
        self.trace = maybe_sample()
        if self.trace is not None:
            _m.trace_requests_sampled.inc()

    def events(self):
        router, payload = self._router, self._payload
        deadline = time.monotonic() + self._timeout
        tried = set()
        req_t0 = time.perf_counter()
        while True:
            rep = router._pick(tried, deadline)
            self.replica = rep.url
            rep.begin()
            t0 = time.perf_counter()
            emitted = False
            # each dispatch attempt is its own span under the request
            # root; its id is what the replica parents its spans under
            attempt = self.trace.child() if self.trace is not None else None
            try:
                try:
                    resp = router._post(rep, payload, self._timeout,
                                        trace=attempt)
                except urllib.error.HTTPError as e:
                    if e.code in _REROUTE_HTTP_CODES:
                        raise ConnectionError(f'replica replied {e.code}')
                    raise                     # client-contract error: 4xx
                _m.router_dispatch_seconds.observe(time.perf_counter() - t0)
                for raw in resp:
                    event = json.loads(raw)
                    if not emitted:
                        emitted = True
                        self.first_event_at = time.monotonic()
                    if event.get('done'):
                        event['replica'] = rep.url
                        event['retries'] = self.retries
                        if self.trace is not None:
                            event.setdefault('trace_id',
                                             self.trace.trace_id)
                            # spans must land BEFORE the done yield: the
                            # consumer may drop the generator right after
                            now = time.perf_counter()
                            _span(attempt, 'router/dispatch', t0, now,
                                  replica=rep.url)
                            _span(self.trace, 'router/request', req_t0,
                                  now, retries=self.retries)
                        _m.router_requests_completed.inc()
                        yield event
                        return
                    if 'error' in event:      # replica-side typed failure
                        _m.router_requests_failed.inc()
                        now = time.perf_counter()
                        _span(attempt, 'router/dispatch', t0, now,
                              replica=rep.url, error=event.get('error'))
                        _span(self.trace, 'router/request', req_t0, now,
                              retries=self.retries,
                              error=event.get('error'))
                        yield event
                        return
                    yield event
                # stream ended with no done line: replica died mid-write
                raise ConnectionError('replica stream ended early')
            except urllib.error.HTTPError:
                # only client-contract 4xx reach here (reroutable codes were
                # converted to ConnectionError above); HTTPError must be
                # caught BEFORE URLError, its base class
                raise
            except (ConnectionError, urllib.error.URLError, OSError) as e:
                rep.mark_dead()
                if emitted:
                    # tokens already forwarded: this stream dies with its
                    # replica (the only thing a replica death may kill)
                    _m.router_requests_failed.inc()
                    now = time.perf_counter()
                    _span(attempt, 'router/dispatch', t0, now,
                          replica=rep.url, error='ReplicaDied')
                    _span(self.trace, 'router/request', req_t0, now,
                          retries=self.retries, error='ReplicaDied')
                    yield {'error': 'ReplicaDied',
                           'message': f'replica {rep.url} failed '
                                      f'mid-stream: {e}',
                           'replica': rep.url, 'retries': self.retries}
                    return
                # nothing streamed yet: reroute, zero client-visible drops
                tried.add(rep)
                self.retries += 1
                _m.router_requests_rerouted.inc()
                # the failed attempt becomes a retry span — the failover
                # drill asserts this sits between the two replicas' spans
                _span(attempt, 'router/retry', t0, time.perf_counter(),
                      replica=rep.url, error=str(e))
                _logger.warning('rerouting (attempt %d) off %s: %s',
                                self.retries + 1, rep.url, e)
            finally:
                rep.end()


class Router:
    """See module docstring. ``replica_urls``: base URLs of the replicas
    (``http://host:port``). ``health_poll_s`` defaults from the strict-parse
    ``PADDLE_TPU_ROUTER_HEALTH_POLL_S`` knob (1.0s)."""

    def __init__(self, replica_urls, health_poll_s=None,
                 request_timeout=120.0, connect_timeout=5.0, start=True):
        if not replica_urls:
            raise ValueError('need at least one replica URL')
        self.replicas = [Replica(u) for u in replica_urls]
        self._replicas_lock = threading.Lock()   # guards membership only
        self.health_poll_s = (parse_float_env(ENV_ROUTER_HEALTH_POLL_S, 1.0)
                              if health_poll_s is None
                              else float(health_poll_s))
        self.request_timeout = float(request_timeout)
        self.connect_timeout = float(connect_timeout)
        _dobs.set_process_label('router')
        self._closed = threading.Event()
        self.poll_once()              # constructor returns with fresh state
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name='paddle-tpu-router-health',
            daemon=True)
        if start:
            self._poll_thread.start()

    # -- health ------------------------------------------------------------
    def _poll_replica(self, rep):
        _m.router_health_polls.inc()
        try:
            u0 = time.time()
            with urllib.request.urlopen(rep.url + '/healthz',
                                        timeout=self.connect_timeout) as r:
                body = json.load(r)
            u1 = time.time()
            rep.healthy = body.get('status') == 'ok'
            rep.half_open = False
            warm = body.get('warmup')
            # replicas predating the warmup field are assumed warm
            rep.warmed = bool(warm.get('done')) if warm else rep.healthy
            decode = body.get('decode') or {}
            rep.reported_load = (int(decode.get('active', 0))
                                 + int(decode.get('waiting', 0)))
            rep.last_poll_ok = time.monotonic()
            rep.replica_id = body.get('replica') or rep.replica_id
            rep.series = body.get('series') or rep.series
            if 'unix_time' in body:
                # handshake offset estimate: the replica stamped its clock
                # somewhere inside [u0, u1]; the RTT midpoint is the
                # minimum-bias guess (error bounded by RTT/2)
                rep.clock_offset = float(body['unix_time']) - (u0 + u1) / 2.0
                _m.trace_clock_offset_seconds.labels(
                    replica=rep.replica_id or rep.url).set(rep.clock_offset)
                _dobs.record_clock_offset(rep.replica_id or rep.url,
                                          rep.clock_offset, rtt_s=u1 - u0)
        except urllib.error.HTTPError as e:
            try:
                body = json.load(e)
            except Exception:
                body = {}
            rep.healthy = False
            # a half-open breaker needs ONE probe request to re-admit the
            # replica; the router is the only traffic source, so it routes
            # exactly one there
            rep.half_open = any(
                s == 'half_open'
                for s in (body.get('breakers') or {}).values())
            rep.last_poll_ok = time.monotonic()
        except OSError:
            rep.mark_dead()
        _m.router_replicas_routable.set(
            sum(r.healthy and r.warmed and not r.draining
                for r in self.replicas))

    def poll_once(self):
        for rep in list(self.replicas):
            self._poll_replica(rep)

    def _poll_loop(self):
        while not self._closed.wait(self.health_poll_s):
            self.poll_once()

    def _fast_poll(self, rep):
        """Admission poll for a freshly added replica: short initial
        backoff (50 ms, doubling up to the regular ``health_poll_s``)
        until the first moment it is routable — so scale-up
        time-to-routable tracks the replica's actual warmup, instead of
        being quantized to a full health-poll period."""
        delay = 0.05
        while not self._closed.wait(delay):
            if rep not in self.replicas:
                return                 # removed before it came up
            self._poll_replica(rep)
            if rep.routable():
                _logger.info('replica %s admitted: routable after fast '
                             'poll', rep.url)
                return
            delay = min(delay * 2, self.health_poll_s)

    # -- elastic membership (elastic/autoscaler.py) ------------------------
    def add_replica(self, url, fast_poll=True):
        """Register a replica at runtime (scale-up). It starts unpolled —
        NOT routable — and is admitted by the fast initial poll the
        moment ``/healthz`` reports healthy + warm (the cold-replica gate
        applies to elastic replicas exactly as to static ones). Returns
        the :class:`Replica` (the existing one if already registered)."""
        url = url.rstrip('/')
        with self._replicas_lock:
            for r in self.replicas:
                if r.url == url:
                    return r
            rep = Replica(url)
            # copy-on-write: readers iterate a stable list snapshot
            self.replicas = self.replicas + [rep]
        if fast_poll:
            threading.Thread(target=self._fast_poll, args=(rep,),
                             name='paddle-tpu-router-admit',
                             daemon=True).start()
        return rep

    def remove_replica(self, url):
        """Deregister a replica (scale-down, after drain). In-flight
        streams keep their handle to it; it just stops being a dispatch
        candidate. Raises KeyError when unknown."""
        url = url.rstrip('/')
        with self._replicas_lock:
            rep = next((r for r in self.replicas if r.url == url), None)
            if rep is None:
                raise KeyError(f'unknown replica {url}')
            self.replicas = [r for r in self.replicas if r is not rep]
        _m.router_replicas_routable.set(
            sum(r.healthy and r.warmed and not r.draining
                for r in self.replicas))
        return rep

    # -- dispatch ----------------------------------------------------------
    def _pick(self, exclude, deadline):
        """Lowest-loaded routable replica, waiting (bounded by ``deadline``)
        through transient all-down windows so momentary blips don't drop
        requests. Raises :class:`NoReplicaAvailable` at the deadline."""
        while True:
            candidates = [r for r in self.replicas
                          if r not in exclude and r.routable()]
            if candidates:
                rep = min(candidates, key=lambda r: r.load())
                if rep.half_open and not rep.healthy:
                    _m.router_probes.inc()
                    _logger.info('routing a probe to half-open replica %s',
                                 rep.url)
                return rep
            _m.router_no_replica.inc()
            if time.monotonic() >= deadline:
                raise NoReplicaAvailable(
                    [r.state() for r in self.replicas])
            # blip window: excluded replicas may recover; re-admit them
            exclude.clear()
            time.sleep(min(0.2, self.health_poll_s))
            self.poll_once()

    def _post(self, rep, payload, timeout, trace=None):
        headers = {'Content-Type': 'application/json'}
        if trace is not None:
            headers.update(trace.to_headers())
        req = urllib.request.Request(
            rep.url + '/generate', data=json.dumps(payload).encode(),
            headers=headers)
        return urllib.request.urlopen(req, timeout=timeout)

    # -- fleet metrics -----------------------------------------------------
    def scrape_replica_metrics(self, timeout_s=2.0):
        """Scrape every replica's ``/metrics``; → ``[(label, text), ...]``
        for the scrapes that succeeded. A dead or wedged replica costs one
        bounded timeout and a ``router_scrape_failures`` tick — never a
        fleet-scrape failure (the kill -9 hardening contract)."""
        scrapes = []
        for rep in self.replicas:
            label = rep.replica_id or rep.url
            try:
                with urllib.request.urlopen(rep.url + '/metrics',
                                            timeout=timeout_s) as r:
                    scrapes.append((label,
                                    r.read().decode('utf-8', 'replace')))
            except (OSError, ValueError) as e:
                _m.router_scrape_failures.labels(replica=label).inc()
                _logger.warning('fleet scrape of %s failed: %s',
                                rep.url, e)
        return scrapes

    def fleet_metrics_text(self, timeout_s=2.0):
        """Merged replica-labeled Prometheus text for ``/metrics/fleet``
        (docs/OBSERVABILITY.md "Aggregation semantics"). Router-local
        metrics stay on ``/metrics`` — this is the REPLICAS' merged
        view, so the two exports never double-count."""
        _m.router_fleet_scrapes.inc()
        return _dobs.merge_fleet_metrics(
            self.scrape_replica_metrics(timeout_s))

    # -- client API --------------------------------------------------------
    def stream_generate(self, prompt, max_new_tokens=16, eos_id=None,
                        timeout_ms=None, timeout=None, temperature=None,
                        top_k=None, top_p=None, seed=None, request_id=None):
        """Route one streaming generation; returns a
        :class:`RoutedGeneration` (consume ``.events()``). Sampling knobs
        forward to the replica's /generate schema; see
        :func:`_attach_sampling` for the sampled-failover identity rule."""
        _m.router_requests.inc()
        payload = {'prompt': list(prompt),
                   'max_new_tokens': int(max_new_tokens), 'stream': True}
        if eos_id is not None:
            payload['eos_id'] = int(eos_id)
        if timeout_ms is not None:
            payload['timeout_ms'] = timeout_ms
        _attach_sampling(payload, temperature, top_k, top_p, seed,
                         request_id)
        return RoutedGeneration(self, payload,
                                timeout or self.request_timeout)

    def generate(self, prompt, max_new_tokens=16, eos_id=None,
                 timeout_ms=None, timeout=None, **sampling):
        """Blocking convenience: route, stream to completion, return the
        final done dict (raises on an error event). ``**sampling`` passes
        temperature/top_k/top_p/seed/request_id through."""
        gen = self.stream_generate(prompt, max_new_tokens, eos_id,
                                   timeout_ms, timeout, **sampling)
        from ..errors import ServingError
        final = None
        for event in gen.events():
            if 'error' in event and not event.get('done'):
                raise ServingError(
                    f"routed generation failed: {event['error']}: "
                    f"{event.get('message')}")
            final = event
        if final is None or not final.get('done'):
            raise NoReplicaAvailable([r.state() for r in self.replicas])
        return final

    def generate_nonstream(self, prompt, max_new_tokens=16, eos_id=None,
                           timeout_ms=None, timeout=None, temperature=None,
                           top_k=None, top_p=None, seed=None,
                           request_id=None):
        """Non-streamed routed generation: the replica replies with ONE
        JSON body, so a failure at ANY point before the reply — connection
        refused, replica killed mid-generation, 5xx — is safely retried on
        another replica (generation is deterministic: greedy exactly, and
        sampled streams replay from the request_id the router stamps —
        so retries are idempotent). Non-streamed requests therefore
        survive a replica death with zero drops even while in flight."""
        _m.router_requests.inc()
        timeout = timeout or self.request_timeout
        payload = {'prompt': list(prompt),
                   'max_new_tokens': int(max_new_tokens), 'stream': False}
        if eos_id is not None:
            payload['eos_id'] = int(eos_id)
        if timeout_ms is not None:
            payload['timeout_ms'] = timeout_ms
        _attach_sampling(payload, temperature, top_k, top_p, seed,
                         request_id)
        deadline = time.monotonic() + timeout
        tried = set()
        retries = 0
        trace = maybe_sample()        # edge decision, as in events()
        if trace is not None:
            _m.trace_requests_sampled.inc()
        req_t0 = time.perf_counter()
        while True:
            rep = self._pick(tried, deadline)
            rep.begin()
            t0 = time.perf_counter()
            attempt = trace.child() if trace is not None else None
            try:
                try:
                    with self._post(rep, payload, timeout,
                                    trace=attempt) as resp:
                        body = json.load(resp)
                except urllib.error.HTTPError as e:
                    if e.code in _REROUTE_HTTP_CODES:
                        raise ConnectionError(f'replica replied {e.code}')
                    raise                     # client-contract error: 4xx
                _m.router_dispatch_seconds.observe(time.perf_counter() - t0)
                body['replica'] = rep.url
                body['retries'] = retries
                if trace is not None:
                    body.setdefault('trace_id', trace.trace_id)
                    now = time.perf_counter()
                    _span(attempt, 'router/dispatch', t0, now,
                          replica=rep.url)
                    _span(trace, 'router/request', req_t0, now,
                          retries=retries)
                _m.router_requests_completed.inc()
                return body
            except urllib.error.HTTPError:
                raise                         # 4xx (see events(): order!)
            except (ConnectionError, urllib.error.URLError, OSError,
                    ValueError) as e:
                rep.mark_dead()
                tried.add(rep)
                retries += 1
                _m.router_requests_rerouted.inc()
                _span(attempt, 'router/retry', t0, time.perf_counter(),
                      replica=rep.url, error=str(e))
                _logger.warning('retrying non-streamed request off %s: %s',
                                rep.url, e)
            finally:
                rep.end()

    # -- operations --------------------------------------------------------
    def drain(self, url):
        self._replica_by_url(url).draining = True

    def undrain(self, url):
        self._replica_by_url(url).draining = False

    def _replica_by_url(self, url):
        url = url.rstrip('/')
        for r in self.replicas:
            if r.url == url:
                return r
        raise KeyError(f'unknown replica {url}')

    def rolling_restart(self, restart_fn, drain_timeout=60.0,
                        warm_timeout=300.0, poll_interval=0.1):
        """Restart every replica one at a time behind a drain: stop routing
        to it, wait for its router-side in-flight work to finish, call
        ``restart_fn(url)`` (which may return the restarted replica's NEW
        url), then wait until it reports healthy AND warm before re-admitting
        it and moving on — traffic keeps flowing through the other replicas
        the whole time."""
        for rep in self.replicas:
            rep.draining = True
            deadline = time.monotonic() + drain_timeout
            while rep.inflight > 0 and time.monotonic() < deadline:
                time.sleep(poll_interval)
            new_url = restart_fn(rep.url)
            if new_url:
                rep.url = str(new_url).rstrip('/')
            rep.healthy = rep.warmed = False
            deadline = time.monotonic() + warm_timeout
            while time.monotonic() < deadline:
                self._poll_replica(rep)
                if rep.healthy and rep.warmed:
                    break
                time.sleep(poll_interval)
            else:
                rep.draining = False
                raise RuntimeError(
                    f'replica {rep.url} did not come back healthy+warm '
                    f'within {warm_timeout}s')
            rep.draining = False
            _m.router_rolling_restarts.inc()
            _logger.info('rolling restart: %s back and warm', rep.url)

    def close(self):
        self._closed.set()
        if self._poll_thread.is_alive():
            self._poll_thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = 'HTTP/1.1'
    server_version = 'paddle-tpu-router'

    def log_message(self, fmt, *args):
        _logger.debug('%s %s', self.address_string(), fmt % args)

    def _reply(self, code, body, content_type='application/json'):
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
        self.send_response(code)
        self.send_header('Content-Type', content_type)
        self.send_header('Content-Length', str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _write_chunk(self, obj):
        data = json.dumps(obj).encode() + b'\n'
        self.wfile.write(b'%x\r\n' % len(data) + data + b'\r\n')
        self.wfile.flush()

    def do_GET(self):
        router = self.server.router
        if self.path == '/healthz':
            states = [r.state() for r in router.replicas]
            routable = sum(r.routable() for r in router.replicas)
            self._reply(200 if routable else 503,
                        {'status': 'ok' if routable else 'no_replicas',
                         'routable': routable, 'replicas': states})
        elif self.path == '/metrics':
            from ...observability import registry
            self._reply(200, registry.prometheus_text().encode(),
                        content_type='text/plain; version=0.0.4')
        elif self.path == '/metrics/fleet':
            self._reply(200, router.fleet_metrics_text().encode(),
                        content_type='text/plain; version=0.0.4')
        else:
            self._reply(404, {'error': 'NotFound', 'message': self.path})

    def do_POST(self):
        if self.path != '/generate':
            return self._reply(404, {'error': 'NotFound',
                                     'message': self.path})
        router = self.server.router
        try:
            length = int(self.headers.get('Content-Length') or 0)
            payload = json.loads(self.rfile.read(length)) if length > 0 \
                else None
        except (ValueError, UnicodeDecodeError):
            payload = None
        if not isinstance(payload, dict) or \
                not isinstance(payload.get('prompt'), list):
            return self._reply(400, {
                'error': 'InvalidRequest',
                'message': 'body must include "prompt": [token ids]'})
        unknown = sorted(set(payload) - _GENERATE_KEYS)
        if unknown:
            return self._reply(400, {
                'error': 'InvalidRequest',
                'message': f'unknown request field(s): {", ".join(unknown)}'
                           f'; supported: '
                           f'{", ".join(sorted(_GENERATE_KEYS))}'})
        stream = payload.get('stream', True) is not False
        try:
            gen = router.stream_generate(
                payload['prompt'],
                max_new_tokens=payload.get('max_new_tokens', 16),
                eos_id=payload.get('eos_id'),
                timeout_ms=payload.get('timeout_ms'),
                **{k: payload[k] for k in (*_SAMPLING_KEYS, 'request_id')
                   if k in payload})
            if not stream:
                events = list(gen.events())
                final = events[-1] if events else {}
                if 'error' in final and not final.get('done'):
                    return self._reply(502, final)
                reply = {
                    'tokens': final.get('tokens', []),
                    'finish_reason': final.get('finish_reason'),
                    'replica': final.get('replica'),
                    'retries': final.get('retries', 0),
                    'request_id': final.get('request_id'),
                    'replica_id': final.get('replica_id')}
                if 'trace_id' in final:   # sampled: hand the id back
                    reply['trace_id'] = final['trace_id']
                return self._reply(200, reply)
            # prime the FIRST event before committing the 200: replica 4xx /
            # no-replica failures raise here, while an error reply is still
            # possible on the wire
            events = gen.events()
            try:
                first = next(events)
            except StopIteration:
                first = None
            self.send_response(200)
            self.send_header('Content-Type', 'application/x-ndjson')
            self.send_header('Transfer-Encoding', 'chunked')
            self.end_headers()
            try:
                if first is not None:
                    self._write_chunk(first)
                for event in events:
                    self._write_chunk(event)
                self.wfile.write(b'0\r\n\r\n')
                self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass                  # client went away
        except NoReplicaAvailable as e:
            self._reply(503, {'error': 'NoReplicaAvailable',
                              'message': str(e)})
        except urllib.error.HTTPError as e:
            # a replica's 4xx client-contract reply, relayed verbatim
            try:
                body = e.read()
            except Exception:
                body = json.dumps({'error': 'HTTPError',
                                   'message': str(e)}).encode()
            self._reply(e.code, body)
        except InvalidRequest as e:
            self._reply(400, {'error': 'InvalidRequest', 'message': str(e)})


class RouterServer:
    """Stdlib HTTP front for a :class:`Router` (same shape as
    serving/server.py): ``POST /generate`` (streamed NDJSON or one JSON
    reply), ``GET /healthz``, ``GET /metrics``. ``port=0`` binds an
    ephemeral port."""

    def __init__(self, router, host='127.0.0.1', port=None):
        if port is None:
            port = parse_int_env(ENV_ROUTER_PORT, 8180, minimum=0,
                                 maximum=65535)
        self.router = router
        self._httpd = ThreadingHTTPServer((host, int(port)), _RouterHandler)
        self._httpd.daemon_threads = True
        self._httpd.router = router
        self._thread = None

    @property
    def port(self):
        return self._httpd.server_address[1]

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name='paddle-tpu-router-http',
                                        daemon=True)
        self._thread.start()
        _logger.info('routing on %s:%d over %d replicas',
                     self._httpd.server_address[0], self.port,
                     len(self.router.replicas))
        return self

    def serve_forever(self):
        _logger.info('routing on %s:%d over %d replicas',
                     self._httpd.server_address[0], self.port,
                     len(self.router.replicas))
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.shutdown()

    def shutdown(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self.router.close()
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description='paddle_tpu serving tier: multi-replica router')
    ap.add_argument('--replica', action='append', default=None,
                    help='replica base URL (repeatable); defaults from '
                         'PADDLE_TPU_ROUTER_REPLICAS')
    ap.add_argument('--host', default='0.0.0.0')
    ap.add_argument('--port', type=int, default=None,
                    help='defaults from PADDLE_TPU_ROUTER_PORT (8180)')
    ap.add_argument('--health-poll-s', type=float, default=None,
                    help='defaults from PADDLE_TPU_ROUTER_HEALTH_POLL_S (1)')
    args = ap.parse_args(argv)
    urls = args.replica or parse_replicas_env(ENV_ROUTER_REPLICAS)
    if not urls:
        ap.error(f'no replicas: pass --replica or set {ENV_ROUTER_REPLICAS}')
    router = Router(urls, health_poll_s=args.health_poll_s)
    scaler = None
    from ...elastic.autoscaler import AutoscaleConfig, Autoscaler
    if AutoscaleConfig.enabled_from_env():      # PADDLE_TPU_AUTOSCALE=1
        from ...elastic.launcher import ProcessReplicaLauncher
        scaler = Autoscaler(router, ProcessReplicaLauncher(),
                            AutoscaleConfig.from_env())
    try:
        RouterServer(router, host=args.host, port=args.port).serve_forever()
    finally:
        if scaler is not None:
            scaler.close()
            scaler.launcher.close()


if __name__ == '__main__':
    main()
