"""Serving tier (docs/SERVING.md "Serving tier"): the planet-scale layer in
front of N engine replicas — ROADMAP item 3.

Three composable pieces:

- :class:`Router` / :class:`RouterServer` (router.py) — least-loaded
  dispatch over replicas using their always-on ``/healthz`` + ``decode_*``
  load, circuit-breaker awareness (degraded drained, half-open probed),
  cold-replica gating on the ``warmup`` field, mid-stream failover with the
  zero-drop first-event rule, and rolling restarts behind drain.
- :class:`PrefixCache` (prefix_cache.py) — radix trie at block granularity
  over the paged KV pool: shared system prompts resolve to already-filled
  refcounted blocks, prefill runs only on the uncached suffix (chunked
  through the lockstep decode step — bitwise parity preserved), LRU
  eviction over refcount-idle blocks. Enable per engine
  (``DecodeEngine(prefix_cache=True)`` / ``PADDLE_TPU_PREFIX_CACHE=1``).
- disaggregated prefill/decode (disagg.py) — :class:`PrefillReplica` runs
  the bucket ladder on a prefill-role engine and ships
  :class:`KVPayload` (whole KV blocks + first token) to decode-role
  replicas through the :class:`LocalPrefillWorker` handoff seam
  (``DecodeScheduler(disagg=...)`` / ``PADDLE_TPU_DISAGG=1``).

Quick start::

    # replicas (one process each)
    python -m paddle_tpu.serving.tier.replica --port 8081
    python -m paddle_tpu.serving.tier.replica --port 8082
    # router
    python -m paddle_tpu.serving.tier.router \
        --replica http://127.0.0.1:8081 --replica http://127.0.0.1:8082
"""
from __future__ import annotations

from .knobs import (parse_flag_env, parse_float_env, parse_int_env,
                    parse_replicas_env)
from .prefix_cache import PrefixCache
from .disagg import KVPayload, LocalPrefillWorker, PrefillReplica
from .router import Replica, RoutedGeneration, Router, RouterServer

__all__ = ['Router', 'RouterServer', 'RoutedGeneration', 'Replica',
           'PrefixCache', 'KVPayload', 'LocalPrefillWorker',
           'PrefillReplica', 'parse_flag_env', 'parse_float_env',
           'parse_int_env', 'parse_replicas_env']
