"""Radix prefix cache over the paged KV pool: shared prompt prefixes resolve
to already-filled, refcounted cache blocks, so prefill runs only on the
uncached suffix (docs/SERVING.md "Serving tier"; kernel-side blueprint:
"Ragged Paged Attention", PAPERS.md arxiv 2604.15464).

Why a trie keyed at BLOCK granularity: K/V rows for position ``p`` depend on
the whole token prefix ``[0..p]`` (attention mixes every earlier position
into layer-1+ activations), so cached K/V is only reusable for a prompt that
matches the ENTIRE prefix leading to it. A radix trie whose edges are
``block_size``-token chunks encodes exactly that: the node reached by
walking a prompt's whole-block chunks holds a block id whose K/V content is
valid for ANY prompt sharing that prefix — and block granularity means a hit
plugs straight into the request's :class:`~..decode.kv_cache.BlockTable`
with zero copying.

Bitwise-parity design (the load-bearing PR 6 contract): the uncached suffix
is NOT run through a second prefill formulation — the scheduler feeds the
remaining prompt tokens through the SAME lockstep ``(S, 1)`` decode step
used for generation (chunked prefill), whose logits rows are already proven
``array_equal`` to the whole-sequence forward at ``padded_context``. A
cached-hit generation therefore emits exactly the cold generation's bytes,
and the parity suite (tests/framework/test_prefix_cache.py) asserts it.

Host spill tier (docs/SERVING.md "Tiered KV cache"): with
``PADDLE_TPU_PREFIX_CACHE_HOST_MB`` > 0, an idle block that would be
evicted is instead SPILLED — serialized to host RAM as a one-block
:class:`~.disagg.KVPayload` (the npz wire format; same bytes a cross-host
handoff would ship) while its trie node stays in place with ``block=None``.
A later radix hit walking through spilled nodes reinjects them: blocks are
reallocated and the whole reinjected run lands with ONE scatter per layer
(``KVCachePool.write_whole_blocks``), so the working set the cache can
serve is host-RAM-sized, not HBM-sized. The host tier is an LRU bounded by
the byte cap; overflowing entries are dropped for real (with their fully-
spilled subtrees). Spilled-subtree invariant: a spilled node never has a
resident descendant — spill victims have none, and both ``match`` (via
reinjection) and ``insert`` (via promotion from the publishing request's
identical private copy) restore residency top-down along any path they
walk.

Invariants:

- only WHOLE blocks of prompt tokens are published (a block also holding
  generated or padded rows is request-private and never enters the trie);
- the last prompt token is never served from cache (``match`` caps at
  ``(P - 1) // block_size`` blocks): at least one token must be fed through
  the model to produce the first generated token's logits;
- refcounts (``kv_cache.BlockAllocator``): a resident block carries the
  cache's own reference plus one per live request sharing it. Spill/evict
  is LRU over **refcount-idle** nodes with no resident children, so
  interior nodes never orphan reachable resident blocks; it triggers on
  pool pressure (an allocation that would otherwise raise OutOfBlocks) and
  on the ``PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS`` cap at publish — counted
  apart as ``prefix_cache_evictions{cause=pressure|cap}``. Nodes on the
  walk that triggered the pressure are excluded from victim selection (an
  eviction there would detach the path being built and leak its blocks).

Metrics (always-on, docs/OBSERVABILITY.md): ``prefix_cache_hits/misses``,
``prefix_cache_tokens_saved`` (prefill-compute-saved),
``prefix_cache_blocks_resident``, ``prefix_cache_inserted_blocks``,
``prefix_cache_evicted_blocks``, ``prefix_cache_evictions{cause}``, and the
spill tier's ``kv_cache_{bytes_spilled,spill_count,reinject_count,
reinject_seconds}``.
"""
from __future__ import annotations

import collections
import itertools
import threading
import time

from .. import metrics as _m
from ..errors import InvalidRequest, OutOfBlocks
from ..decode.kv_cache import BlockTable
from .knobs import (ENV_PREFIX_CACHE_HOST_MB, ENV_PREFIX_CACHE_MAX_BLOCKS,
                    parse_int_env)

__all__ = ['PrefixCache']


class _Node:
    __slots__ = ('block', 'children', 'parent', 'chunk', 'last_use')

    def __init__(self, block, parent=None, chunk=None):
        self.block = block            # pool block id; None = spilled (or root)
        self.children = {}            # chunk tuple -> _Node
        self.parent = parent
        self.chunk = chunk            # this node's edge key in parent
        self.last_use = 0


class _HostTier:
    """Byte-bounded LRU of spilled one-block payloads, keyed by trie node.
    Overflow returns the DROPPED nodes so the cache can unlink their
    (fully-spilled) subtrees — a payload the LRU let go of must not leave a
    dangling trie path that ``match`` would try to reinject."""

    def __init__(self, cap_bytes):
        self.cap = int(cap_bytes)
        self.bytes = 0
        self._entries = collections.OrderedDict()   # _Node -> payload bytes

    def __len__(self):
        return len(self._entries)

    def __contains__(self, node):
        return node in self._entries

    def put(self, node, blob):
        self._entries[node] = blob
        self._entries.move_to_end(node)
        self.bytes += len(blob)
        dropped = []
        while self.bytes > self.cap and self._entries:
            n, b = self._entries.popitem(last=False)
            self.bytes -= len(b)
            dropped.append(n)
        return dropped

    def pop(self, node):
        blob = self._entries.pop(node)
        self.bytes -= len(blob)
        return blob

    def touch(self, node):
        if node in self._entries:
            self._entries.move_to_end(node)


class PrefixCache:
    """Token-trie prefix cache bound to one :class:`KVCachePool`.

    The intended owner is a :class:`~..decode.engine.DecodeEngine` (enable
    with ``DecodeEngine(prefix_cache=True)`` or ``PADDLE_TPU_PREFIX_CACHE=1``);
    all calls arrive on the scheduler worker thread, but a lock keeps
    direct multi-threaded engine use correct.

    ``max_blocks``: resident-block cap (0 = uncapped, bounded only by pool
    pressure); defaults from ``PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS``.
    ``host_mb``: host spill-tier byte cap (0 = no spill tier, idle blocks
    under pressure are dropped as before); defaults from
    ``PADDLE_TPU_PREFIX_CACHE_HOST_MB``.
    """

    def __init__(self, pool, max_blocks=None, host_mb=None):
        self.pool = pool
        self.block_size = pool.block_size
        self.max_blocks = (parse_int_env(ENV_PREFIX_CACHE_MAX_BLOCKS, 0,
                                         minimum=0)
                           if max_blocks is None else int(max_blocks))
        host_mb = (parse_int_env(ENV_PREFIX_CACHE_HOST_MB, 0, minimum=0)
                   if host_mb is None else int(host_mb))
        self._host = _HostTier(host_mb << 20) if host_mb else None
        self._root = _Node(None)
        self._resident = 0
        self._clock = itertools.count(1)
        self._lock = threading.RLock()

    # -- introspection -----------------------------------------------------
    @property
    def resident_blocks(self):
        return self._resident

    @property
    def spilled_blocks(self):
        """Blocks currently living in the host tier (0 when it is off)."""
        return len(self._host) if self._host is not None else 0

    @property
    def host_bytes(self):
        return self._host.bytes if self._host is not None else 0

    def resident_block_ids(self):
        with self._lock:
            out = []
            stack = list(self._root.children.values())
            while stack:
                n = stack.pop()
                if n.block is not None:
                    out.append(n.block)
                stack.extend(n.children.values())
            return out

    # -- lookup ------------------------------------------------------------
    def match(self, prompt):
        """Longest cached whole-block prefix of ``prompt``, RETAINED for the
        caller (one reference per block). Returns the block-id list; at
        most ``(len(prompt) - 1) // block_size`` blocks so at least one
        prompt token is always left to feed. Spilled nodes on the hit path
        are reinjected from the host tier (the path truncates at the first
        spilled node the pool cannot make room for)."""
        bs = self.block_size
        usable = max(len(prompt) - 1, 0) // bs
        with self._lock:
            node, path = self._root, []
            for i in range(usable):
                child = node.children.get(tuple(prompt[i * bs:(i + 1) * bs]))
                if child is None:
                    break
                path.append(child)
                node = child
            path = self._reinject_path(path)
            blocks = [n.block for n in path]
            # stamp the whole hit path as one recency unit (leaf-first LRU
            # then naturally evicts deepest, least-shared nodes first)
            tick = next(self._clock)
            n = path[-1] if path else self._root
            while n is not None and n is not self._root:
                n.last_use = tick
                n = n.parent
            if blocks:
                self.pool.allocator.retain(blocks)
        if blocks:
            _m.prefix_cache_hits.inc()
            _m.prefix_cache_tokens_saved.inc(len(blocks) * bs)
        else:
            _m.prefix_cache_misses.inc()
        return blocks

    def _reinject_path(self, path):
        """Restore residency for spilled nodes on a hit path: allocate a
        block each (spilling/evicting NON-path idles under pressure), then
        scatter all reinjected payloads with one ``write_whole_blocks``
        per layer. Returns the (possibly truncated) usable path."""
        if not any(n.block is None for n in path):
            return path
        from .disagg import KVPayload
        t0 = time.perf_counter()
        exclude = set(map(id, path))
        pending = []                       # [node, new block id, payload]
        try:
            for idx, n in enumerate(path):
                if n.block is not None:
                    continue
                # ``exclude`` shields path nodes from VICTIM selection
                # only: a pressure spill below can still overflow the
                # host LRU and drop a later path node (this one
                # included) — so check membership before allocating and
                # after.
                bid = None
                if n in self._host:
                    try:
                        bid = self._allocate_evicting(1, exclude=exclude)[0]
                    except OutOfBlocks:
                        bid = None
                    if bid is not None and n not in self._host:
                        self.pool.allocator.release([bid])
                        bid = None
                if bid is None:
                    # truncate here; the still-spilled tail was just
                    # matched (hot), so refresh its host-LRU recency
                    for m in path[idx:]:
                        if m.block is None and m in self._host:
                            self._host.touch(m)
                    path = path[:idx]
                    break
                pending.append([n, bid, None])
                pending[-1][2] = KVPayload.from_bytes(self._host.pop(n))
            if not pending:
                return path
            import numpy as np
            ids = [bid for _, bid, _ in pending]
            n_layers = max(len(p.layers) for _, _, p in pending)
            for layer in range(n_layers):
                k = np.concatenate([p.layers[layer][0]
                                    for _, _, p in pending], axis=1)
                v = np.concatenate([p.layers[layer][1]
                                    for _, _, p in pending], axis=1)
                ks = vs = None
                if pending[0][2].scales is not None:
                    ks = np.concatenate(
                        [p.scales[layer][0] for _, _, p in pending], axis=1)
                    vs = np.concatenate(
                        [p.scales[layer][1] for _, _, p in pending], axis=1)
                self.pool.write_whole_blocks(layer, ids, k, v,
                                             k_scale=ks, v_scale=vs)
        except BaseException:
            # the pending payloads are already popped from the host
            # tier: return their blocks to the pool and drop the now-
            # irrecoverable nodes so a later match cannot dangle on them
            self.pool.allocator.release([bid for _, bid, _ in pending])
            for m, _, _ in pending:
                self._drop_spilled(m)
            raise
        for n, bid, _ in pending:
            # the fresh allocation's refcount 1 becomes the cache's own
            # residency reference (mirror of insert's retain)
            n.block = bid
            self._resident += 1
        _m.prefix_cache_blocks_resident.set(self._resident)
        _m.kv_cache_reinject_count.inc(len(pending))
        _m.kv_cache_reinject_seconds.observe(time.perf_counter() - t0)
        return path

    # -- admission ---------------------------------------------------------
    def acquire_table(self, prompt, total_tokens):
        """Build a request's :class:`BlockTable` for ``total_tokens``
        (prompt + generation budget): shared cached-prefix blocks first,
        freshly allocated blocks for the rest. Pool pressure spills (or
        evicts) idle cached blocks before giving up (the re-raised
        OutOfBlocks is the scheduler's FIFO-wait signal, unchanged)."""
        bs = self.block_size
        nb = -(-int(total_tokens) // bs)
        if nb > self.pool.max_blocks_per_seq:
            raise InvalidRequest(
                f'{total_tokens} tokens need {nb} blocks > '
                f'max_blocks_per_seq={self.pool.max_blocks_per_seq}')
        with self._lock:
            shared = self.match(prompt) if prompt else []
            try:
                fresh = self._allocate_evicting(nb - len(shared))
            except OutOfBlocks:
                if shared:
                    self.pool.allocator.release(shared)
                raise
        return BlockTable(shared + fresh, bs,
                          cached_len=len(shared) * bs)

    def _allocate_evicting(self, n, exclude=frozenset()):
        while True:
            try:
                return self.pool.allocator.allocate(n)
            except OutOfBlocks:
                if not self._spill_or_evict_one(exclude=exclude,
                                                cause='pressure'):
                    raise

    # -- publication -------------------------------------------------------
    def insert(self, prompt, table):
        """Publish ``table``'s whole-prompt blocks into the trie. Blocks
        already cached along the path are skipped (the request keeps its
        private copy in its table — content is identical by construction);
        new nodes retain their block so it survives the request. A SPILLED
        node on the path is promoted back to residency from the request's
        private copy (same content, zero deserialization). The
        ``PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS`` cap is enforced here too
        (cause=``cap``), with the walked path excluded from victim
        selection — evicting a node this very walk stands on would attach
        the new child to a detached subtree and leak its block."""
        bs = self.block_size
        full = len(prompt) // bs
        tick = next(self._clock)
        with self._lock:
            node = self._root
            walked = {id(self._root)}
            for i in range(full):
                chunk = tuple(int(t) for t in prompt[i * bs:(i + 1) * bs])
                child = node.children.get(chunk)
                needs_block = child is None or child.block is None
                if needs_block:
                    if self.max_blocks and self._resident >= self.max_blocks:
                        if not self._spill_or_evict_one(exclude=walked,
                                                        cause='cap'):
                            break     # cap reached, nothing idle to move
                    bid = table.blocks[i]
                    self.pool.allocator.retain([bid])
                    if child is None:
                        child = _Node(bid, parent=node, chunk=chunk)
                        node.children[chunk] = child
                    else:             # promote the spilled node in place
                        child.block = bid
                        if self._host is not None and child in self._host:
                            self._host.pop(child)
                    self._resident += 1
                    _m.prefix_cache_inserted_blocks.inc()
                child.last_use = tick
                walked.add(id(child))
                node = child
            _m.prefix_cache_blocks_resident.set(self._resident)

    # -- spill / eviction --------------------------------------------------
    def _spill_or_evict_one(self, exclude=frozenset(), cause='pressure',
                            allow_spill=True):
        """Move the least-recently-used idle node (block refcount == 1, no
        resident children — the spilled-subtree invariant keeps deeper
        descendants non-resident too) out of HBM: into the host tier when
        it is configured and ``allow_spill``, else dropped. ``exclude``
        holds ``id()``s of nodes the caller's walk depends on. Returns
        False when nothing is movable."""
        victim = None
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if (n.block is not None and id(n) not in exclude
                    and all(c.block is None for c in n.children.values())
                    and self.pool.allocator.refcount(n.block) == 1):
                if victim is None or n.last_use < victim.last_use:
                    victim = n
        if victim is None:
            return False
        bid = victim.block
        if self._host is not None and allow_spill:
            self._spill(victim)         # sets victim.block = None
        else:
            self._unlink(victim)
        self.pool.allocator.release([bid])
        self._resident -= 1
        _m.prefix_cache_evicted_blocks.inc()
        _m.prefix_cache_evictions.labels(cause=cause).inc()
        _m.prefix_cache_blocks_resident.set(self._resident)
        return True

    def _evict_one(self, exclude=frozenset(), cause='pressure'):
        """Pre-spill name, kept for callers/tests that poke the eviction
        machinery directly: move one idle block out of HBM (into the host
        tier when configured)."""
        return self._spill_or_evict_one(exclude=exclude, cause=cause)

    def _spill(self, node):
        """Serialize ``node``'s single block to the host tier as a
        one-block :class:`~.disagg.KVPayload` (the npz wire bytes a
        cross-host handoff would ship) and leave the node in place with
        ``block=None``. The block itself is released by the caller."""
        from .disagg import KVPayload
        pool = self.pool
        bid = node.block
        layers, scales, any_scales = [], [], False
        for layer in range(pool.num_layers):
            layers.append(pool.read_blocks(layer, [bid]))
            sc = pool.read_block_scales(layer, [bid])
            scales.append(sc)
            any_scales = any_scales or sc is not None
        payload = KVPayload(layers, self.block_size, 0, self.block_size,
                            kv_dtype=pool.kv_dtype,
                            scales=scales if any_scales else None)
        blob = payload.to_bytes()
        node.block = None
        for dropped in self._host.put(node, blob):
            # the LRU let this payload go — its trie path (fully spilled
            # by the invariant) must go with it or match would dangle
            self._drop_spilled(dropped)
        _m.kv_cache_spill_count.inc()
        _m.kv_cache_bytes_spilled.inc(len(blob))

    def _unlink(self, node):
        """Remove ``node`` from the trie. Its children are all spilled
        (victim selection guarantees no resident ones) and become
        unreachable — drop them from the host tier with it."""
        if node.parent is not None:
            del node.parent.children[node.chunk]
        for child in list(node.children.values()):
            self._drop_spilled(child)

    def _drop_spilled(self, node):
        """Discard a spilled node and its (spilled) subtree entirely."""
        if node.parent is not None and node.parent.children.get(
                node.chunk) is node:
            del node.parent.children[node.chunk]
        stack = [node]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if self._host is not None and n in self._host:
                self._host.pop(n)

    def evict_idle(self):
        """Drop every currently-idle cached block for real — no spilling
        (tests / shutdown want the pool AND host tier shrinking)."""
        with self._lock:
            n = 0
            while self._spill_or_evict_one(allow_spill=False):
                n += 1
            # fully-spilled subtrees have no resident node for the loop to
            # unlink through — drop them outright so host RAM drains too
            if self._host is not None:
                stack = [self._root]
                while stack:
                    node = stack.pop()
                    for child in list(node.children.values()):
                        if child.block is None:
                            self._drop_spilled(child)
                        else:
                            stack.append(child)
            return n
