"""Radix prefix cache over the paged KV pool: shared prompt prefixes resolve
to already-filled, refcounted cache blocks, so prefill runs only on the
uncached suffix (docs/SERVING.md "Serving tier"; kernel-side blueprint:
"Ragged Paged Attention", PAPERS.md arxiv 2604.15464).

Why a trie keyed at BLOCK granularity: K/V rows for position ``p`` depend on
the whole token prefix ``[0..p]`` (attention mixes every earlier position
into layer-1+ activations), so cached K/V is only reusable for a prompt that
matches the ENTIRE prefix leading to it. A radix trie whose edges are
``block_size``-token chunks encodes exactly that: the node reached by
walking a prompt's whole-block chunks holds a block id whose K/V content is
valid for ANY prompt sharing that prefix — and block granularity means a hit
plugs straight into the request's :class:`~..decode.kv_cache.BlockTable`
with zero copying.

Bitwise-parity design (the load-bearing PR 6 contract): the uncached suffix
is NOT run through a second prefill formulation — the scheduler feeds the
remaining prompt tokens through the SAME lockstep ``(S, 1)`` decode step
used for generation (chunked prefill), whose logits rows are already proven
``array_equal`` to the whole-sequence forward at ``padded_context``. A
cached-hit generation therefore emits exactly the cold generation's bytes,
and the parity suite (tests/framework/test_prefix_cache.py) asserts it.

Invariants:

- only WHOLE blocks of prompt tokens are published (a block also holding
  generated or padded rows is request-private and never enters the trie);
- the last prompt token is never served from cache (``match`` caps at
  ``(P - 1) // block_size`` blocks): at least one token must be fed through
  the model to produce the first generated token's logits;
- refcounts (``kv_cache.BlockAllocator``): a resident block carries the
  cache's own reference plus one per live request sharing it. Eviction is
  LRU over **refcount-idle leaves** (blocks whose only reference is the
  cache's), leaf-first so interior nodes never orphan reachable children;
  it triggers on pool pressure (an allocation that would otherwise raise
  OutOfBlocks) and on the ``PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS`` cap.

Metrics (always-on, docs/OBSERVABILITY.md): ``prefix_cache_hits/misses``,
``prefix_cache_tokens_saved`` (prefill-compute-saved),
``prefix_cache_blocks_resident``, ``prefix_cache_inserted_blocks``,
``prefix_cache_evicted_blocks``.
"""
from __future__ import annotations

import itertools
import threading

from .. import metrics as _m
from ..errors import InvalidRequest, OutOfBlocks
from ..decode.kv_cache import BlockTable
from .knobs import ENV_PREFIX_CACHE_MAX_BLOCKS, parse_int_env

__all__ = ['PrefixCache']


class _Node:
    __slots__ = ('block', 'children', 'parent', 'chunk', 'last_use')

    def __init__(self, block, parent=None, chunk=None):
        self.block = block            # pool block id (None only at root)
        self.children = {}            # chunk tuple -> _Node
        self.parent = parent
        self.chunk = chunk            # this node's edge key in parent
        self.last_use = 0


class PrefixCache:
    """Token-trie prefix cache bound to one :class:`KVCachePool`.

    The intended owner is a :class:`~..decode.engine.DecodeEngine` (enable
    with ``DecodeEngine(prefix_cache=True)`` or ``PADDLE_TPU_PREFIX_CACHE=1``);
    all calls arrive on the scheduler worker thread, but a lock keeps
    direct multi-threaded engine use correct.

    ``max_blocks``: resident-block cap (0 = uncapped, bounded only by pool
    pressure); defaults from ``PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS``.
    """

    def __init__(self, pool, max_blocks=None):
        self.pool = pool
        self.block_size = pool.block_size
        self.max_blocks = (parse_int_env(ENV_PREFIX_CACHE_MAX_BLOCKS, 0,
                                         minimum=0)
                           if max_blocks is None else int(max_blocks))
        self._root = _Node(None)
        self._resident = 0
        self._clock = itertools.count(1)
        self._lock = threading.RLock()

    # -- introspection -----------------------------------------------------
    @property
    def resident_blocks(self):
        return self._resident

    def resident_block_ids(self):
        with self._lock:
            out = []
            stack = list(self._root.children.values())
            while stack:
                n = stack.pop()
                out.append(n.block)
                stack.extend(n.children.values())
            return out

    # -- lookup ------------------------------------------------------------
    def match(self, prompt):
        """Longest cached whole-block prefix of ``prompt``, RETAINED for the
        caller (one reference per block). Returns the block-id list; at
        most ``(len(prompt) - 1) // block_size`` blocks so at least one
        prompt token is always left to feed."""
        bs = self.block_size
        usable = max(len(prompt) - 1, 0) // bs
        with self._lock:
            node, blocks = self._root, []
            for i in range(usable):
                child = node.children.get(tuple(prompt[i * bs:(i + 1) * bs]))
                if child is None:
                    break
                blocks.append(child.block)
                node = child
            # stamp the whole hit path as one recency unit (leaf-first LRU
            # then naturally evicts deepest, least-shared nodes first)
            tick = next(self._clock)
            while node is not self._root:
                node.last_use = tick
                node = node.parent
            if blocks:
                self.pool.allocator.retain(blocks)
        if blocks:
            _m.prefix_cache_hits.inc()
            _m.prefix_cache_tokens_saved.inc(len(blocks) * bs)
        else:
            _m.prefix_cache_misses.inc()
        return blocks

    # -- admission ---------------------------------------------------------
    def acquire_table(self, prompt, total_tokens):
        """Build a request's :class:`BlockTable` for ``total_tokens``
        (prompt + generation budget): shared cached-prefix blocks first,
        freshly allocated blocks for the rest. Pool pressure evicts idle
        cached blocks before giving up (the re-raised OutOfBlocks is the
        scheduler's FIFO-wait signal, unchanged)."""
        bs = self.block_size
        nb = -(-int(total_tokens) // bs)
        if nb > self.pool.max_blocks_per_seq:
            raise InvalidRequest(
                f'{total_tokens} tokens need {nb} blocks > '
                f'max_blocks_per_seq={self.pool.max_blocks_per_seq}')
        with self._lock:
            shared = self.match(prompt) if prompt else []
            try:
                fresh = self._allocate_evicting(nb - len(shared))
            except OutOfBlocks:
                if shared:
                    self.pool.allocator.release(shared)
                raise
        return BlockTable(shared + fresh, bs,
                          cached_len=len(shared) * bs)

    def _allocate_evicting(self, n):
        while True:
            try:
                return self.pool.allocator.allocate(n)
            except OutOfBlocks:
                if not self._evict_one():
                    raise

    # -- publication -------------------------------------------------------
    def insert(self, prompt, table):
        """Publish ``table``'s whole-prompt blocks into the trie. Blocks
        already cached along the path are skipped (the request keeps its
        private copy in its table — content is identical by construction);
        new nodes retain their block so it survives the request."""
        bs = self.block_size
        full = len(prompt) // bs
        tick = next(self._clock)
        with self._lock:
            node = self._root
            for i in range(full):
                chunk = tuple(int(t) for t in prompt[i * bs:(i + 1) * bs])
                child = node.children.get(chunk)
                if child is None:
                    if self.max_blocks and self._resident >= self.max_blocks:
                        if not self._evict_one():
                            break     # cap reached, nothing idle to drop
                    bid = table.blocks[i]
                    self.pool.allocator.retain([bid])
                    child = _Node(bid, parent=node, chunk=chunk)
                    node.children[chunk] = child
                    self._resident += 1
                    _m.prefix_cache_inserted_blocks.inc()
                child.last_use = tick
                node = child
            _m.prefix_cache_blocks_resident.set(self._resident)

    # -- eviction ----------------------------------------------------------
    def _evict_one(self):
        """Drop the least-recently-used idle leaf (block refcount == 1, the
        cache's own). Leaf-only keeps every remaining node reachable; the
        caller loops. Returns False when nothing is evictable."""
        victim = None
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif self.pool.allocator.refcount(n.block) == 1:
                if victim is None or n.last_use < victim.last_use:
                    victim = n
        if victim is None:
            return False
        del victim.parent.children[victim.chunk]
        self.pool.allocator.release([victim.block])
        self._resident -= 1
        _m.prefix_cache_evicted_blocks.inc()
        _m.prefix_cache_blocks_resident.set(self._resident)
        return True

    def evict_idle(self):
        """Drop every currently-idle cached block (tests / shutdown)."""
        with self._lock:
            n = 0
            while self._evict_one():
                n += 1
            return n
