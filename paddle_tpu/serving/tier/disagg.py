"""Disaggregated prefill/decode: prefill-role replicas run the bucket
ladder and ship finished KV blocks + the first greedy token to decode-role
replicas, so long prompts stop stalling the lockstep ``(S, 1)`` decode step
(docs/SERVING.md "Serving tier").

Why split the phases: prefill and decode want opposite shapes. Prefill is
one big bucket-padded forward (compute-bound, O(P²) attention); decode is a
tiny fixed-shape step whose latency IS the per-token latency of every
active stream. Colocated, each admission's prefill runs between decode
steps and every active stream's next token waits behind it. Disaggregated,
the scheduler marks the admitted slot handoff-pending and keeps stepping;
a prefill worker runs the prompt on its OWN engine/pool and hands back a
:class:`KVPayload`; the decode worker injects the whole blocks (one scatter
per layer) and the stream starts.

The HANDOFF INTERFACE is the seam: :class:`LocalPrefillWorker` is the
in-process transport (threads + queues — the form a single-host deployment
uses, and what the parity tests pin); :meth:`KVPayload.to_bytes` /
:meth:`KVPayload.from_bytes` define the wire format a cross-host transport
ships, so a network hop slots in behind the same
``submit``/``drain_completed`` contract without touching the scheduler.

Bitwise parity: the prefill engine runs the SAME model weights and the same
bucket-padded matmul formulation, so the shipped K/V bytes equal what a
colocated prefill would have written — the decode trajectory is
``array_equal``-identical to colocated and to the uncached whole-sequence
reference (tests/framework/test_disagg.py).
"""
from __future__ import annotations

import io
import queue
import threading
import time

import numpy as np

from .. import metrics as _m
from ..errors import ServingError

__all__ = ['KVPayload', 'PrefillReplica', 'LocalPrefillWorker']


class KVPayload:
    """One finished prefill (or one spilled prefix-cache block): whole KV
    blocks for every layer + the first greedy token. ``layers[i]`` is
    ``(k, v)`` with shape (H, num_blocks, block_size, D) — the
    :meth:`KVCachePool.read_blocks` layout, scatter-ready on the decode
    side.

    ``kv_dtype`` records the sender pool's storage dtype
    (``PADDLE_TPU_KV_DTYPE``); for int8 pools ``scales[i]`` is the
    ``(k_scales, v_scales)`` pair of (H, num_blocks, block_size) f32
    row scales (``read_block_scales``) — shipping the quantized payload +
    scales keeps a same-dtype handoff byte-exact AND ~4× smaller on the
    wire than the f32 bytes it replaces."""

    __slots__ = ('layers', 'context_len', 'first_token', 'block_size',
                 'kv_dtype', 'scales')

    def __init__(self, layers, context_len, first_token, block_size,
                 kv_dtype='f32', scales=None):
        self.layers = layers
        self.context_len = int(context_len)
        self.first_token = int(first_token)
        self.block_size = int(block_size)
        self.kv_dtype = kv_dtype
        self.scales = scales          # per-layer (k_scales, v_scales) | None

    @property
    def num_blocks(self):
        return self.layers[0][0].shape[1] if self.layers else 0

    @property
    def nbytes(self):
        total = sum(k.nbytes + v.nbytes for k, v in self.layers)
        if self.scales is not None:
            total += sum(ks.nbytes + vs.nbytes
                         for ks, vs in self.scales if ks is not None)
        return total

    # -- wire format (the cross-host seam) ---------------------------------
    def to_bytes(self):
        from ..decode.kv_cache import KV_DTYPE_CODES
        arrays = {'meta': np.asarray(
            [self.context_len, self.first_token, self.block_size,
             KV_DTYPE_CODES[self.kv_dtype]], np.int64)}
        for i, (k, v) in enumerate(self.layers):
            k, v = np.asarray(k), np.asarray(v)
            if k.dtype.name == 'bfloat16':
                # npz has no portable bf16; ship as f32 (a lossless widen —
                # the receiving pool re-narrows to identical bf16 bytes)
                k, v = k.astype(np.float32), v.astype(np.float32)
            arrays[f'k{i}'] = k
            arrays[f'v{i}'] = v
            if self.scales is not None and self.scales[i] is not None:
                arrays[f'ks{i}'] = np.asarray(self.scales[i][0])
                arrays[f'vs{i}'] = np.asarray(self.scales[i][1])
        buf = io.BytesIO()
        # wire serialization into memory — no file, torn-write-proof
        # commit does not apply
        np.savez(buf, **arrays)  # lint: allow-io (in-memory BytesIO, not a file)
        return buf.getvalue()

    @classmethod
    def from_bytes(cls, data):
        from ..decode.kv_cache import KV_DTYPE_CODES
        codes = {v: k for k, v in KV_DTYPE_CODES.items()}
        with np.load(io.BytesIO(data)) as z:
            meta = [int(x) for x in z['meta']]
            ctx, first, bs = meta[:3]
            # pre-quantization senders wrote a 3-int meta: f32 payload
            kv_dtype = codes[meta[3]] if len(meta) > 3 else 'f32'
            layers, scales, any_scales = [], [], False
            i = 0
            while f'k{i}' in z:
                layers.append((z[f'k{i}'], z[f'v{i}']))
                if f'ks{i}' in z:
                    scales.append((z[f'ks{i}'], z[f'vs{i}']))
                    any_scales = True
                else:
                    scales.append(None)
                i += 1
        return cls(layers, ctx, first, bs, kv_dtype=kv_dtype,
                   scales=scales if any_scales else None)


class PrefillReplica:
    """Prefill-role wrapper around a :class:`DecodeEngine`: its pool is
    scratch space — blocks live only from prefill to payload extraction,
    then free. One worker thread owns it (``LocalPrefillWorker``)."""

    def __init__(self, engine):
        self.engine = engine

    def prefill_to_payload(self, prompt, max_new_tokens=0):
        """Run the bucket-padded prompt on the prefill engine, read the
        finished blocks out, free them, return the :class:`KVPayload`."""
        eng = self.engine
        bs = eng.pool.block_size
        table = eng.pool.new_table(len(prompt))   # prompt only: scratch use
        try:
            first = eng.prefill(prompt, table)
            nb = -(-len(prompt) // bs)
            layers, scales, any_scales = [], [], False
            for layer in range(eng.pool.num_layers):
                layers.append(eng.pool.read_blocks(layer, table.blocks[:nb]))
                sc = eng.pool.read_block_scales(layer, table.blocks[:nb])
                scales.append(sc)
                any_scales = any_scales or sc is not None
        finally:
            eng.release_table(table)
        return KVPayload(layers, len(prompt), first, bs,
                         kv_dtype=eng.pool.kv_dtype,
                         scales=scales if any_scales else None)


class LocalPrefillWorker:
    """In-process handoff transport: a worker thread pool running
    :class:`PrefillReplica` jobs, feeding a completion queue the decode
    scheduler drains between steps.

    Contract consumed by ``DecodeScheduler(disagg=...)``:

    - ``submit(key, prompt, max_new_tokens)`` — enqueue one prefill; never
      blocks the caller.
    - ``drain_completed(timeout)`` — all finished ``(key, payload, exc)``
      triples; ``exc`` is a typed ServingError when the prefill failed
      (the request fails, the decode loop keeps serving).
    """

    def __init__(self, prefill_replicas, start=True):
        if not isinstance(prefill_replicas, (list, tuple)):
            prefill_replicas = [prefill_replicas]
        self.replicas = [r if isinstance(r, PrefillReplica)
                         else PrefillReplica(r) for r in prefill_replicas]
        self._jobs = queue.Queue()
        self._done = queue.Queue()
        self._closing = False
        self._pending = 0
        self._lock = threading.Lock()
        self._threads = [
            threading.Thread(target=self._run, args=(rep,),
                             name=f'paddle-tpu-prefill-worker-{i}',
                             daemon=True)
            for i, rep in enumerate(self.replicas)]
        if start:
            for t in self._threads:
                t.start()

    @property
    def pending(self):
        with self._lock:
            return self._pending

    def submit(self, key, prompt, max_new_tokens=0):
        with self._lock:
            self._pending += 1
            _m.disagg_pending.set(self._pending)
        self._jobs.put((key, list(prompt), int(max_new_tokens),
                        time.perf_counter()))

    def drain_completed(self, timeout=0.0):
        out = []
        deadline = time.monotonic() + timeout
        while True:
            try:
                remaining = deadline - time.monotonic()
                if out or remaining <= 0:
                    out.append(self._done.get_nowait())
                else:
                    out.append(self._done.get(timeout=remaining))
            except queue.Empty:
                return out

    def _run(self, replica):
        while True:
            job = self._jobs.get()
            if job is None:
                return
            key, prompt, max_new, t0 = job
            payload, exc = None, None
            try:
                payload = replica.prefill_to_payload(prompt, max_new)
            except Exception as e:
                exc = e if isinstance(e, ServingError) else ServingError(
                    f'disaggregated prefill failed: '
                    f'{type(e).__name__}: {e}')
                _m.disagg_handoff_failures.inc()
            with self._lock:
                self._pending -= 1
                _m.disagg_pending.set(self._pending)
            if payload is not None:
                _m.disagg_handoffs.inc()
                _m.disagg_kv_bytes.inc(payload.nbytes)
                _m.disagg_handoff_seconds.observe(time.perf_counter() - t0)
            self._done.put((key, payload, exc))

    def close(self):
        self._closing = True
        for _ in self._threads:
            self._jobs.put(None)
        for t in self._threads:
            t.join(timeout=10)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
