"""Strict-parse env knobs for the serving tier (house style per
PRs 9/12: a malformed value raises ValueError naming the knob and listing
the supported set, instead of silently falling back while the operator
believes the knob took effect).

Parsed at USE time (constructors / CLI mains), never at import — a bad
environment must fail the component that reads it, not every
``import paddle_tpu``.

| knob | form | used by |
|---|---|---|
| ``PADDLE_TPU_PREFIX_CACHE``            | ``0`` / ``1``          | DecodeEngine |
| ``PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS`` | int >= 0 (0 = uncapped)| PrefixCache |
| ``PADDLE_TPU_DISAGG``                  | ``0`` / ``1``          | tier/replica.py |
| ``PADDLE_TPU_ROUTER_REPLICAS``         | comma list of http URLs| tier/router.py CLI |
| ``PADDLE_TPU_ROUTER_PORT``             | int in [0, 65535]      | tier/router.py CLI |
| ``PADDLE_TPU_ROUTER_HEALTH_POLL_S``    | float > 0              | Router |
| ``PADDLE_TPU_SPEC_DECODE``             | ``0`` / ``1``          | DecodeEngine (``0`` is the hard escape hatch — wins over the constructor arg) |
| ``PADDLE_TPU_SPEC_K``                  | int >= 2               | DecodeEngine (verify-window width) |
| ``PADDLE_TPU_SPEC_DRAFTER``            | ``ngram`` / ``draft_model`` / ``off`` | DecodeScheduler |
| ``PADDLE_TPU_KV_DTYPE``                | ``f32`` / ``bf16`` / ``int8`` | KVCachePool storage dtype (docs/SERVING.md "Tiered KV cache") |
| ``PADDLE_TPU_DECODE_HBM_MB``           | int > 0                | DecodeEngine pool sizing (budget solve; explicit ``PADDLE_TPU_DECODE_MAX_BLOCKS`` / ``max_blocks=`` wins) |
| ``PADDLE_TPU_PREFIX_CACHE_HOST_MB``    | int >= 0 (0 = no spill tier) | PrefixCache host spill tier byte cap |
| ``PADDLE_TPU_AUTOSCALE``               | ``0`` / ``1``          | router CLI: run an elastic Autoscaler beside the router |
| ``PADDLE_TPU_AUTOSCALE_MIN``           | int >= 1               | Autoscaler floor (default 1) |
| ``PADDLE_TPU_AUTOSCALE_MAX``           | int >= 1               | Autoscaler ceiling (default 4) |
| ``PADDLE_TPU_AUTOSCALE_INTERVAL_S``    | float > 0              | control-loop tick (default 1.0) |
| ``PADDLE_TPU_AUTOSCALE_UP_QUEUE``      | float > 0              | scale-up: mean queue depth per routable replica (default 4.0) |
| ``PADDLE_TPU_AUTOSCALE_UP_TTFT_S``     | float > 0              | scale-up: p99 time-to-first-token seconds (default 2.0) |
| ``PADDLE_TPU_AUTOSCALE_DOWN_OCC``      | float > 0              | scale-down: mean slot occupancy below this (default 0.25) |
| ``PADDLE_TPU_AUTOSCALE_COOLDOWN_S``    | float > 0              | min seconds between decisions (default 10) |
| ``PADDLE_TPU_AUTOSCALE_DOWN_DELAY_S``  | float > 0              | sustained-low seconds before a scale-down (default 30) |
| ``PADDLE_TPU_TRACE_SAMPLE``            | float in [0, 1]        | router edge sampling (observability/trace_context.py) |
| ``PADDLE_TPU_TRACE_DIR``               | directory path         | span-record JSONL output (observability/distributed.py) |
| ``PADDLE_TPU_SLO``                     | ``<series>.<agg><op><value>,...`` | ServingServer /healthz (observability/distributed.py SLOMonitor) |

The trace/SLO knobs' parsers live beside their consumers in
``observability/`` (this package imports observability, never the
reverse) but follow the same strict-parse contract.
"""
from __future__ import annotations

import os

__all__ = ['parse_flag_env', 'parse_int_env', 'parse_float_env',
           'parse_replicas_env', 'parse_choice_env', 'ENV_PREFIX_CACHE',
           'ENV_PREFIX_CACHE_MAX_BLOCKS', 'ENV_DISAGG', 'ENV_ROUTER_REPLICAS',
           'ENV_ROUTER_PORT', 'ENV_ROUTER_HEALTH_POLL_S', 'ENV_SPEC_DECODE',
           'ENV_SPEC_K', 'ENV_SPEC_DRAFTER', 'ENV_KV_DTYPE',
           'ENV_DECODE_HBM_MB', 'ENV_PREFIX_CACHE_HOST_MB',
           'KV_DTYPE_CHOICES', 'ENV_AUTOSCALE', 'ENV_AUTOSCALE_MIN',
           'ENV_AUTOSCALE_MAX', 'ENV_AUTOSCALE_INTERVAL_S',
           'ENV_AUTOSCALE_UP_QUEUE', 'ENV_AUTOSCALE_UP_TTFT_S',
           'ENV_AUTOSCALE_DOWN_OCC', 'ENV_AUTOSCALE_COOLDOWN_S',
           'ENV_AUTOSCALE_DOWN_DELAY_S']

ENV_PREFIX_CACHE = 'PADDLE_TPU_PREFIX_CACHE'
ENV_PREFIX_CACHE_MAX_BLOCKS = 'PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS'
ENV_KV_DTYPE = 'PADDLE_TPU_KV_DTYPE'
ENV_DECODE_HBM_MB = 'PADDLE_TPU_DECODE_HBM_MB'
ENV_PREFIX_CACHE_HOST_MB = 'PADDLE_TPU_PREFIX_CACHE_HOST_MB'

# the KV-cache storage dtypes kv_cache.KVCachePool accepts, in quality
# order: f32 is the bitwise-exact default, bf16 halves payload bytes with
# exact-roundtrip-through-f32 semantics, int8 quarters them behind one f32
# scale per (head, position) row (quant_collectives.rowwise_quantize)
KV_DTYPE_CHOICES = ('f32', 'bf16', 'int8')
ENV_DISAGG = 'PADDLE_TPU_DISAGG'
ENV_ROUTER_REPLICAS = 'PADDLE_TPU_ROUTER_REPLICAS'
ENV_ROUTER_PORT = 'PADDLE_TPU_ROUTER_PORT'
ENV_ROUTER_HEALTH_POLL_S = 'PADDLE_TPU_ROUTER_HEALTH_POLL_S'
ENV_SPEC_DECODE = 'PADDLE_TPU_SPEC_DECODE'
ENV_SPEC_K = 'PADDLE_TPU_SPEC_K'
ENV_SPEC_DRAFTER = 'PADDLE_TPU_SPEC_DRAFTER'

# elastic autoscaler (elastic/autoscaler.py; docs/SERVING.md "Autoscaler")
ENV_AUTOSCALE = 'PADDLE_TPU_AUTOSCALE'
ENV_AUTOSCALE_MIN = 'PADDLE_TPU_AUTOSCALE_MIN'
ENV_AUTOSCALE_MAX = 'PADDLE_TPU_AUTOSCALE_MAX'
ENV_AUTOSCALE_INTERVAL_S = 'PADDLE_TPU_AUTOSCALE_INTERVAL_S'
ENV_AUTOSCALE_UP_QUEUE = 'PADDLE_TPU_AUTOSCALE_UP_QUEUE'
ENV_AUTOSCALE_UP_TTFT_S = 'PADDLE_TPU_AUTOSCALE_UP_TTFT_S'
ENV_AUTOSCALE_DOWN_OCC = 'PADDLE_TPU_AUTOSCALE_DOWN_OCC'
ENV_AUTOSCALE_COOLDOWN_S = 'PADDLE_TPU_AUTOSCALE_COOLDOWN_S'
ENV_AUTOSCALE_DOWN_DELAY_S = 'PADDLE_TPU_AUTOSCALE_DOWN_DELAY_S'


def parse_flag_env(name, default=False, environ=None):
    """``0``/``1`` boolean knob; anything else raises listing the set."""
    raw = (environ if environ is not None else os.environ).get(name, '')
    raw = raw.strip()
    if not raw:
        return bool(default)
    if raw not in ('0', '1'):
        raise ValueError(
            f"{name}={raw!r} is not supported; supported values: '0', '1'")
    return raw == '1'


def parse_int_env(name, default, minimum=0, maximum=None, environ=None):
    raw = (environ if environ is not None else os.environ).get(name, '')
    raw = raw.strip()
    if not raw:
        return int(default)
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(
            f'{name}={raw!r} is not supported; supported values: integers '
            f'>= {minimum}' + (f' and <= {maximum}' if maximum is not None
                               else ''))
    if val < minimum or (maximum is not None and val > maximum):
        raise ValueError(
            f'{name}={val} out of range; supported values: integers '
            f'>= {minimum}' + (f' and <= {maximum}' if maximum is not None
                               else ''))
    return val


def parse_float_env(name, default, minimum_exclusive=0.0, environ=None):
    raw = (environ if environ is not None else os.environ).get(name, '')
    raw = raw.strip()
    if not raw:
        return float(default)
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(
            f'{name}={raw!r} is not supported; supported values: numbers '
            f'> {minimum_exclusive}')
    if not val > minimum_exclusive:
        raise ValueError(
            f'{name}={val} out of range; supported values: numbers '
            f'> {minimum_exclusive}')
    return val


def parse_choice_env(name, choices, default, environ=None):
    """Enumerated string knob; a value outside ``choices`` raises listing
    the supported set."""
    raw = (environ if environ is not None else os.environ).get(name, '')
    raw = raw.strip()
    if not raw:
        return default
    if raw not in choices:
        raise ValueError(
            f'{name}={raw!r} is not supported; supported values: '
            + ', '.join(repr(c) for c in choices))
    return raw


def parse_replicas_env(name=ENV_ROUTER_REPLICAS, default=None, environ=None):
    """Comma list of replica base URLs. Each entry must be ``http://host:port``
    (or bare ``host:port``, normalized); a malformed entry raises."""
    raw = (environ if environ is not None else os.environ).get(name, '')
    raw = raw.strip()
    if not raw:
        return list(default) if default else []
    urls = []
    for entry in raw.split(','):
        entry = entry.strip()
        if not entry:
            raise ValueError(
                f'{name} has an empty entry; supported values: comma list '
                f'of http://host:port replica URLs')
        if not entry.startswith(('http://', 'https://')):
            if ':' not in entry:
                raise ValueError(
                    f'{name} entry {entry!r} is not supported; supported '
                    f'values: http://host:port URLs or host:port pairs')
            entry = 'http://' + entry
        urls.append(entry.rstrip('/'))
    return urls
