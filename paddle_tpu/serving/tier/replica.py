"""Canonical decode replica process for the serving tier: a seeded tiny
causal LM behind a ``DecodeScheduler`` + ``ServingServer``, launched as

    python -m paddle_tpu.serving.tier.replica --port 0 --seed 1234

Why it exists: router failover and rolling-restart drills need REAL replica
processes with IDENTICAL weights — the tier's bitwise-parity contract is
"any replica answers any request with the same bytes", which only holds if
every process builds the same parameters. :func:`build_tiny_lm` pins that:
it reseeds the global key generator before construction, so every process
(and every in-process replica in tests/bench) draws the same init stream.

On start the replica prints ONE JSON line to stdout —
``{"ready": true, "port": N, "pid": P, "replica_id": ...}`` — then serves
until killed (the failover test kill -9s exactly this process). Warmup runs
BEFORE the ready line by default so the router's cold-replica gate sees a
warm replica immediately; ``--lazy-warmup`` serves first and warms in a
background thread (how the warmup-gating test produces a cold-but-alive
replica).

Knobs consumed here (strict parse, tier/knobs.py): ``PADDLE_TPU_PREFIX_CACHE``
(via DecodeEngine), ``PADDLE_TPU_DISAGG`` (build a prefill-role engine +
LocalPrefillWorker beside the decode engine), and the speculative-decoding
set ``PADDLE_TPU_SPEC_DECODE`` / ``PADDLE_TPU_SPEC_K`` (via DecodeEngine) +
``PADDLE_TPU_SPEC_DRAFTER`` (via DecodeScheduler) — also exposed as
``--spec-decode`` / ``--spec-k`` / ``--drafter`` CLI flags.

Observability flows through the environment the launcher hands this
process: ``PADDLE_TPU_TRACE_DIR`` makes the replica stream span records
(named by its replica_id via the ServingServer process label) and
``PADDLE_TPU_SLO`` adds the /healthz slo block — the ready line echoes
``trace_dir`` so drills can assert the wiring took.
"""
from __future__ import annotations

import json
import sys
import threading

__all__ = ['build_tiny_lm', 'build_replica_stack', 'main']

DEFAULT_SEED = 1234


def build_tiny_lm(seed=DEFAULT_SEED):
    """A ``TransformerLM(CausalLMConfig.tiny())`` with process-independent
    weights: the global key generator is reseeded first, so any two
    processes (or two sequential builds in ONE process) get bitwise-equal
    parameters."""
    from ...core.random import default_generator
    from ...models.causal_lm import CausalLMConfig, TransformerLM
    default_generator.seed(int(seed))
    model = TransformerLM(CausalLMConfig.tiny())
    model.eval()
    return model


def build_replica_stack(model=None, seed=DEFAULT_SEED, slots=2, block_size=4,
                        max_blocks=128, max_prompt_len=16,
                        max_new_tokens_cap=16, prompt_buckets=None,
                        prefix_cache=None, disagg=None, queue_depth=64,
                        replica_id=None, model_lock=None, spec_decode=None,
                        spec_k=None, drafter=None):
    """(engine, scheduler, prefill_worker|None) — the replica's serving
    stack minus the HTTP listener. ``prefix_cache``/``disagg`` default to
    their env knobs. Used by the CLI below and, in-process, by
    tests/framework/test_serving_tier.py and tools/bench_router.py
    (in-process multi-replica setups pass ONE shared ``model_lock`` so
    concurrent scheduler workers serialize their model calls)."""
    from ..decode import DecodeEngine, DecodeScheduler
    from .knobs import ENV_DISAGG, parse_flag_env
    if model is None:
        model = build_tiny_lm(seed)
    if disagg is None:
        disagg = parse_flag_env(ENV_DISAGG, default=False)
    if model_lock is None and disagg:
        model_lock = threading.RLock()
    engine = DecodeEngine(model, slots=slots, block_size=block_size,
                          max_blocks=max_blocks,
                          max_prompt_len=max_prompt_len,
                          max_new_tokens_cap=max_new_tokens_cap,
                          prompt_buckets=prompt_buckets,
                          prefix_cache=prefix_cache, model_lock=model_lock,
                          spec_decode=spec_decode, spec_k=spec_k)
    worker = None
    if disagg:
        from .disagg import LocalPrefillWorker, PrefillReplica
        # prefill-role engine: same model + weights, its OWN scratch pool;
        # the shared lock serializes the two engines' model calls (the
        # dygraph no-grad flag is process-global)
        prefill_engine = DecodeEngine(
            model, slots=1, block_size=block_size, max_blocks=max_blocks,
            max_prompt_len=max_prompt_len,
            max_new_tokens_cap=max_new_tokens_cap,
            prompt_buckets=prompt_buckets, prefix_cache=False,
            model_lock=model_lock)
        worker = LocalPrefillWorker([PrefillReplica(prefill_engine)])
    scheduler = DecodeScheduler(engine, queue_depth=queue_depth,
                                replica_id=replica_id, disagg=worker,
                                drafter=drafter)
    return engine, scheduler, worker


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description='paddle_tpu serving-tier decode replica (seeded tiny LM)')
    ap.add_argument('--host', default='127.0.0.1')
    ap.add_argument('--port', type=int, default=0)
    ap.add_argument('--seed', type=int, default=DEFAULT_SEED)
    ap.add_argument('--slots', type=int, default=2)
    ap.add_argument('--block-size', type=int, default=4)
    ap.add_argument('--max-blocks', type=int, default=128)
    ap.add_argument('--max-prompt-len', type=int, default=16)
    ap.add_argument('--max-new-tokens-cap', type=int, default=16)
    ap.add_argument('--replica-id', default=None)
    ap.add_argument('--spec-decode', type=int, choices=(0, 1), default=None,
                    help='speculative decoding on/off (default: the '
                         'PADDLE_TPU_SPEC_DECODE knob, off; env 0 always '
                         'wins — the escape hatch)')
    ap.add_argument('--spec-k', type=int, default=None,
                    help='speculative verify window (default: '
                         'PADDLE_TPU_SPEC_K, 4)')
    ap.add_argument('--drafter', default=None,
                    choices=('ngram', 'draft_model', 'off'),
                    help='draft proposer (default: PADDLE_TPU_SPEC_DRAFTER, '
                         'ngram)')
    ap.add_argument('--lazy-warmup', action='store_true',
                    help='serve immediately and warm in the background '
                         '(replica starts COLD: the router must not route '
                         'to it until /healthz warmup.done flips)')
    args = ap.parse_args(argv)

    from ...dygraph import guard
    from ..server import ServingServer
    with guard():
        engine, scheduler, worker = build_replica_stack(
            seed=args.seed, slots=args.slots, block_size=args.block_size,
            max_blocks=args.max_blocks, max_prompt_len=args.max_prompt_len,
            max_new_tokens_cap=args.max_new_tokens_cap,
            replica_id=args.replica_id,
            spec_decode=(None if args.spec_decode is None
                         else bool(args.spec_decode)),
            spec_k=args.spec_k, drafter=args.drafter)
        srv = ServingServer(None, host=args.host, port=args.port,
                            generator=scheduler)
        if args.lazy_warmup:
            threading.Thread(target=engine.warmup, daemon=True,
                             name='paddle-tpu-replica-warmup').start()
        else:
            engine.warmup()
        import os
        # the launcher (router test / bench / operator script) parses this
        # single stdout line to learn the bound port and pid
        from ...observability.trace_context import ENV_TRACE_DIR
        print(json.dumps({'ready': True, 'port': srv.port,  # lint: allow-print (launcher handshake)
                          'pid': os.getpid(),
                          'replica_id': scheduler.replica_id,
                          'trace_dir': os.environ.get(ENV_TRACE_DIR)}),
              flush=True)
        try:
            srv.serve_forever()
        finally:
            if worker is not None:
                worker.close()


if __name__ == '__main__':
    main()
