"""Serving subsystem: dynamic micro-batching inference (docs/SERVING.md).

Three layers, composable or standalone:

- :class:`InferenceEngine` (engine.py) — a saved model behind a **bucketed
  batch ladder**: the batch dim pads up to 1/2/4/…/max, so the compile count
  is bounded and every bucket rides the persistent XLA compile cache;
  ``warmup()`` precompiles the ladder.
- :class:`MicroBatcher` (batcher.py) — bounded request queue + worker thread
  coalescing requests into one device call per batch, with pre-enqueue
  validation, per-request deadlines, ``Overloaded`` backpressure, and
  graceful draining shutdown.
- :class:`ServingServer` (server.py) — stdlib ThreadingHTTPServer front end:
  ``/predict`` (JSON), ``/generate`` (chunked per-token streaming),
  ``/healthz``, ``/metrics`` (Prometheus text).
- **stateful decode** (decode/ — docs/SERVING.md "Stateful decode"):
  :class:`DecodeEngine` + :class:`DecodeScheduler`, autoregressive
  generation over a paged KV cache with slot-based continuous batching
  and per-request :class:`GenerationStream` token streams.
- **serving tier** (tier/ — docs/SERVING.md "Serving tier"):
  :class:`Router` over N replicas (least-loaded, breaker-aware, mid-stream
  failover, rolling restarts), :class:`PrefixCache` (radix prefix sharing
  over the paged KV pool), and disaggregated prefill/decode
  (:class:`LocalPrefillWorker` handoff seam).

Quick start::

    from paddle_tpu import serving
    engine = serving.InferenceEngine('/path/to/saved_model',
                                     max_batch_size=16)
    engine.warmup()
    with serving.MicroBatcher(engine, batch_timeout_ms=2) as batcher:
        out, = batcher.predict({'x': one_row})           # sync
        fut = batcher.submit({'x': rows}, timeout_ms=50)  # async + deadline

or the whole stack: ``python -m paddle_tpu.serving.server --model-dir …``.
"""
from __future__ import annotations

from .errors import (DeadlineExceeded, EngineClosed, EngineUnhealthy,
                     InvalidRequest, NoReplicaAvailable, Overloaded,
                     OutOfBlocks, ServingError)
from .engine import DEFAULT_MAX_BATCH, InferenceEngine, bucket_ladder
from .batcher import (DEFAULT_BATCH_TIMEOUT_MS, DEFAULT_QUEUE_DEPTH,
                      MicroBatcher, PredictionFuture)
from .breaker import CircuitBreaker
from .server import ServingServer, create_server
from .decode import (DecodeEngine, DecodeScheduler, GenerationStream,
                     KVCachePool, NGramDrafter, SamplingParams)
from .tier import (KVPayload, LocalPrefillWorker, PrefillReplica,
                   PrefixCache, Router, RouterServer)

__all__ = ['InferenceEngine', 'MicroBatcher', 'PredictionFuture',
           'ServingServer', 'create_server', 'bucket_ladder',
           'CircuitBreaker',
           'DecodeEngine', 'DecodeScheduler', 'GenerationStream',
           'KVCachePool', 'SamplingParams', 'NGramDrafter',
           'Router', 'RouterServer', 'PrefixCache', 'KVPayload',
           'LocalPrefillWorker', 'PrefillReplica',
           'ServingError', 'InvalidRequest', 'Overloaded', 'DeadlineExceeded',
           'EngineClosed', 'EngineUnhealthy', 'OutOfBlocks',
           'NoReplicaAvailable',
           'DEFAULT_MAX_BATCH', 'DEFAULT_BATCH_TIMEOUT_MS',
           'DEFAULT_QUEUE_DEPTH']
