"""Typed serving errors.

Every failure mode of the serving path maps to exactly one exception type so
callers (and the HTTP front end) can distinguish *your request is bad*
(InvalidRequest), *the system is protecting itself* (Overloaded), *you asked
for a latency we could not meet* (DeadlineExceeded), and *we are going away*
(EngineClosed). All derive from ServingError; the multiple-inheritance bases
(ValueError / TimeoutError) keep generic ``except`` clauses working.
"""
from __future__ import annotations

__all__ = ['ServingError', 'InvalidRequest', 'Overloaded', 'DeadlineExceeded',
           'EngineClosed', 'EngineUnhealthy', 'OutOfBlocks',
           'NoReplicaAvailable']


class ServingError(RuntimeError):
    """Base class for every serving-layer failure."""


class InvalidRequest(ServingError, ValueError):
    """Request rejected at validation time, BEFORE enqueue — a malformed
    request never reaches a batch, so it can never poison co-batched
    requests. Maps to HTTP 400."""


class Overloaded(ServingError):
    """Bounded-queue backpressure: the request queue is full. The request was
    NOT enqueued; the client should back off and retry. Maps to HTTP 429."""

    def __init__(self, queue_depth):
        super().__init__(
            f'serving queue full ({queue_depth} requests waiting); '
            f'back off and retry')
        self.queue_depth = queue_depth


class DeadlineExceeded(ServingError, TimeoutError):
    """The request's deadline expired while it waited in the queue — it was
    dropped before wasting device time. Maps to HTTP 504."""


class EngineClosed(ServingError):
    """Submitted after shutdown began. In-flight requests at shutdown are
    drained, not dropped; new ones get this. Maps to HTTP 503."""


class EngineUnhealthy(ServingError):
    """The circuit breaker is OPEN: the engine failed enough consecutive
    batches that feeding it more requests would only burn their deadlines
    (serving/breaker.py). Rejected in O(µs), BEFORE the queue; the client
    should fail over to another replica — a half-open probe re-admits
    traffic automatically once the engine answers again. Maps to HTTP 503
    (and flips ``/healthz`` to ``degraded``)."""

    def __init__(self, name='engine', failures=None):
        detail = (f' after {failures} consecutive failed batches'
                  if failures else '')
        super().__init__(
            f'{name} circuit breaker is open{detail}; '
            f'failing fast instead of queueing onto a broken engine')
        self.failures = failures


class NoReplicaAvailable(ServingError):
    """The serving-tier router found no routable replica — every replica is
    cold, draining, degraded, or dead — and the wait window expired. Maps
    to HTTP 503; clients back off and retry (tier/router.py)."""

    def __init__(self, replica_states=None):
        states = ''
        if replica_states:
            states = '; replicas: ' + ', '.join(
                f"{s['url']} (healthy={s['healthy']} warmed={s['warmed']} "
                f"draining={s['draining']})" for s in replica_states)
        super().__init__(
            f'no routable replica (all cold, draining, degraded, or '
            f'dead){states}')
        self.replica_states = replica_states


class OutOfBlocks(ServingError):
    """The paged KV-cache pool cannot cover a block reservation right now.
    Inside the decode scheduler this is a WAIT signal (the request stays
    queued until finishing slots free their blocks), never a client error;
    it only escapes to callers driving a DecodeEngine directly."""

    def __init__(self, requested, available):
        super().__init__(
            f'KV cache pool exhausted: need {requested} blocks, '
            f'{available} free (raise PADDLE_TPU_DECODE_MAX_BLOCKS or '
            f'lower concurrency)')
        self.requested = requested
        self.available = available
