"""InferenceEngine: bucketed-batch inference over a saved model.

The single-request :class:`~paddle_tpu.inference.Predictor` compiles one XLA
program per feed-shape set — fine for a script, fatal for a server where
every distinct batch size would be a fresh multi-second compile. The engine
fixes the shape problem the way TPU serving systems do (cf. Ragged Paged
Attention, PAPERS.md): it pads the batch dimension up to a small **bucket
ladder** (1, 2, 4, …, max_batch_size by default), so

- the number of compiled programs is bounded by ``len(buckets)`` forever,
- every bucket's executable flows through the persistent XLA compile cache
  (PR 1), so a restarted server deserializes instead of recompiling,
- :meth:`warmup` precompiles the whole ladder before traffic arrives.

Row results are bitwise-identical to single-request ``Predictor.run``:
per-row ops (matmul rows, row-wise activations, inference-mode norm) do not
mix rows, and padding replicates the last real row so pad lanes stay inside
the data distribution (no log(0)/NaN surprises in models with row-local
nonlinearities). The parity suite in tests/framework/test_serving.py asserts
bitwise equality for every bucket.

Thread-safety: :meth:`run_batch` serializes on an internal lock. The
intended topology is ONE caller — the micro-batcher worker thread
(batcher.py); the lock only keeps direct multi-threaded use correct, not
fast.
"""
from __future__ import annotations

import os
import threading
import time

import numpy as np

from . import metrics as _m
from .errors import InvalidRequest
from ..inference import Predictor, _to_bf16

__all__ = ['InferenceEngine', 'bucket_ladder', 'DEFAULT_MAX_BATCH']

DEFAULT_MAX_BATCH = int(os.environ.get('PADDLE_TPU_SERVING_MAX_BATCH', '16'))


def bucket_ladder(max_batch_size, buckets=None):
    """The padded batch sizes the engine compiles. Default: powers of two up
    to ``max_batch_size``, with ``max_batch_size`` always the top rung (e.g.
    max 12 → [1, 2, 4, 8, 12]). A custom ladder is validated: positive,
    strictly increasing, topped by ``max_batch_size``."""
    max_batch_size = int(max_batch_size)
    if max_batch_size < 1:
        raise ValueError(f'max_batch_size must be >= 1, got {max_batch_size}')
    if buckets is None:
        ladder, b = [], 1
        while b < max_batch_size:
            ladder.append(b)
            b *= 2
        ladder.append(max_batch_size)
        return ladder
    ladder = [int(b) for b in buckets]
    if not ladder or sorted(set(ladder)) != ladder:
        raise ValueError(f'buckets must be strictly increasing, got {buckets}')
    if ladder[0] < 1 or ladder[-1] != max_batch_size:
        raise ValueError(
            f'buckets must start >= 1 and end at max_batch_size='
            f'{max_batch_size}, got {buckets}')
    return ladder


class InferenceEngine:
    """Bucketed-batch wrapper around a saved inference model.

    ``config_or_dir``: a model directory or :class:`inference.Config` (so the
    bf16 / weight-only-int8 deployment paths work unchanged). The model loads
    into a private Scope; device calls pass it explicitly to the Executor —
    no global scope_guard, so concurrent *training* work in the same process
    is unaffected.
    """

    def __init__(self, config_or_dir, executor=None, max_batch_size=None,
                 buckets=None):
        self.max_batch_size = int(max_batch_size or DEFAULT_MAX_BATCH)
        self.buckets = bucket_ladder(self.max_batch_size, buckets)
        self._predictor = Predictor(config_or_dir, executor)
        self.config = self._predictor.config
        self.program = self._predictor.program
        self.feed_names = list(self._predictor.feed_names)
        self.fetch_vars = self._predictor.fetch_vars
        self._exe = self._predictor._exe
        self._scope = self._predictor._scope
        self._lock = threading.Lock()
        self._compiled_buckets = set()
        block = self.program.global_block()
        # {feed name: (per-row tail shape with None for free dims, np.dtype)}
        self.input_spec = {}
        for name in self.feed_names:
            v = block.var(name)
            tail = tuple(None if d == -1 else int(d) for d in v.shape[1:])
            self.input_spec[name] = (tail, np.dtype(v.dtype))
        # {feed name: (vocab, table name)} for feeds that index an
        # embedding table directly: an out-of-range id silently clips to
        # row vocab-1 on device (lookup_table kernel) — validate() rejects
        # it at the door unless PADDLE_TPU_EMBED_OOB=clip (docs/SPARSE.md)
        self.id_bounds = {}
        for op in block.ops:
            if op.type not in ('lookup_table', 'fused_embedding_seq_pool'):
                continue
            ids = (op.inputs.get('ids') or [None])[0]
            w = (op.inputs.get('w') or [None])[0]
            if ids in self.input_spec and w and block.has_var(w):
                shape = block.var(w).shape or ()
                if shape and isinstance(shape[0], int) and shape[0] > 0:
                    self.id_bounds[ids] = (int(shape[0]), w)

    # -- request validation (BEFORE enqueue — batcher.py calls this) -------
    def validate(self, inputs):
        """Normalize ``inputs`` (dict name→array, or list in feed order) to
        ``(feed dict of np arrays with a leading batch dim, nrows)``.
        Raises :class:`InvalidRequest` on anything that could fail inside
        the compiled step, so one bad request can never poison a batch."""
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != len(self.feed_names):
                raise InvalidRequest(
                    f'expected {len(self.feed_names)} inputs '
                    f'{self.feed_names}, got {len(inputs)}')
            inputs = dict(zip(self.feed_names, inputs))
        if not isinstance(inputs, dict):
            raise InvalidRequest(
                f'inputs must be a dict or list, got {type(inputs).__name__}')
        missing = set(self.feed_names) - set(inputs)
        extra = set(inputs) - set(self.feed_names)
        if missing or extra:
            raise InvalidRequest(
                f'feed-name mismatch: missing {sorted(missing)}, '
                f'unknown {sorted(extra)} (expected {self.feed_names})')
        feed, nrows = {}, None
        for name in self.feed_names:
            tail, dtype = self.input_spec[name]
            try:
                arr = np.asarray(inputs[name])
            except Exception as e:
                raise InvalidRequest(f"input '{name}' is not array-like: {e}")
            if arr.dtype == object:
                raise InvalidRequest(
                    f"input '{name}' is not numeric (object array)")
            if arr.ndim != len(tail) + 1:
                raise InvalidRequest(
                    f"input '{name}' must have rank {len(tail) + 1} "
                    f"(batch dim + per-row shape {tail}), got shape "
                    f"{arr.shape}")
            for i, (want, have) in enumerate(zip(tail, arr.shape[1:])):
                if want is not None and want != have:
                    raise InvalidRequest(
                        f"input '{name}' dim {i + 1} must be {want}, got "
                        f"{have} (shape {arr.shape})")
            try:
                arr = arr.astype(dtype, copy=False)
            except (TypeError, ValueError) as e:
                raise InvalidRequest(
                    f"input '{name}' does not cast to {dtype}: {e}")
            if dtype == np.int64:
                # int64 computes as int32 on device (core/dtypes.py); the
                # executor would raise mid-batch — reject at the door instead
                from ..core.dtypes import check_int32_bounds
                try:
                    check_int32_bounds(arr, name)
                except Exception as e:
                    raise InvalidRequest(str(e))
            if name in self.id_bounds and arr.size:
                from ..ops.sparse_ops import oob_policy
                vocab, table = self.id_bounds[name]
                if oob_policy() == 'error' \
                        and (arr.min() < 0 or arr.max() >= vocab):
                    raise InvalidRequest(
                        f"input '{name}' holds ids outside [0, {vocab}) "
                        f"for embedding table '{table}' (min {arr.min()}, "
                        f"max {arr.max()}); on device they would silently "
                        f"clip to row {vocab - 1}. Set "
                        f"PADDLE_TPU_EMBED_OOB=clip for the legacy "
                        f"clipping behavior.")
            if nrows is None:
                nrows = arr.shape[0]
            elif arr.shape[0] != nrows:
                raise InvalidRequest(
                    f"inconsistent batch dims: '{name}' has {arr.shape[0]} "
                    f'rows, earlier inputs have {nrows}')
            feed[name] = arr
        if nrows == 0:
            raise InvalidRequest('empty request (0 rows)')
        if nrows > self.max_batch_size:
            raise InvalidRequest(
                f'request has {nrows} rows > max_batch_size='
                f'{self.max_batch_size}; split it client-side')
        return feed, nrows

    def bucket_for(self, nrows):
        """Smallest ladder rung that fits ``nrows``."""
        for b in self.buckets:
            if nrows <= b:
                return b
        raise InvalidRequest(
            f'{nrows} rows exceed the top bucket {self.buckets[-1]}')

    # -- execution ---------------------------------------------------------
    def run_batch(self, feed, nrows=None):
        """Run one coalesced batch: pad the batch dim up to the bucket, one
        device call, slice the padding back off. ``feed``: validated dict of
        np arrays sharing a leading batch dim. Returns a list of np arrays
        (fetch order), each with ``nrows`` rows."""
        if nrows is None:
            nrows = next(iter(feed.values())).shape[0]
        bucket = self.bucket_for(nrows)
        pad = bucket - nrows
        if pad:
            # replicate the last real row: keeps pad lanes on-distribution
            # (an all-zeros row can hit log(0)/0-division in real models)
            feed = {n: np.concatenate([a, np.repeat(a[-1:], pad, axis=0)])
                    for n, a in feed.items()}
        if self.config.precision == 'bfloat16':
            feed = {k: _to_bf16(v) for k, v in feed.items()}
        with self._lock:
            first = bucket not in self._compiled_buckets
            t0 = time.perf_counter()
            outs = self._exe.run(self.program, feed=feed,
                                 fetch_list=self.fetch_vars,
                                 scope=self._scope)
            dt = time.perf_counter() - t0
            if first:
                self._compiled_buckets.add(bucket)
                _m.bucket_compiled.labels(bucket=bucket).set(1)
                _m.bucket_compile_seconds.labels(bucket=bucket).set(dt)
        _m.bucket_runs.labels(bucket=bucket).inc()
        _m.compute_seconds.labels(bucket=bucket).observe(dt)
        _m.batch_rows.observe(nrows)
        _m.padding_waste_ratio.observe(pad / bucket)
        return [np.asarray(o)[:nrows] for o in outs]

    def infer(self, inputs):
        """Validate + run one request directly (no batcher). The convenience
        path for scripts; servers go through :class:`batcher.MicroBatcher`."""
        feed, nrows = self.validate(inputs)
        return self.run_batch(feed, nrows)

    def warmup(self, example=None):
        """Precompile every bucket before traffic arrives. ``example``: a
        one-row feed dict to tile (required when an input has free non-batch
        dims — the engine cannot invent those sizes). Returns
        {bucket: first-run seconds}; re-running is cheap (all cache hits).
        Each compile goes through the persistent XLA compile cache, so a
        restarted server warms from disk instead of the compiler."""
        if example is not None:
            row, _ = self.validate(example)
            row = {n: a[:1] for n, a in row.items()}
        else:
            row = {}
            for name, (tail, dtype) in self.input_spec.items():
                if any(d is None for d in tail):
                    raise ValueError(
                        f"input '{name}' has free dims {tail}; pass "
                        f'warmup(example={{...}}) with a representative row')
                row[name] = np.zeros((1,) + tail, dtype)
        timings = {}
        for bucket in self.buckets:
            feed = {n: np.repeat(a, bucket, axis=0) for n, a in row.items()}
            t0 = time.perf_counter()
            self.run_batch(feed, nrows=bucket)
            timings[bucket] = time.perf_counter() - t0
        return timings

    @property
    def compiled_buckets(self):
        return sorted(self._compiled_buckets)

    @property
    def warmed(self):
        """True once every ladder rung has compiled (warmup or traffic) —
        surfaced through /healthz for the serving-tier router's cold-replica
        gate."""
        return all(b in self._compiled_buckets for b in self.buckets)

    def get_input_names(self):
        return list(self.feed_names)

    def get_output_names(self):
        return [v.name if hasattr(v, 'name') else v for v in self.fetch_vars]
