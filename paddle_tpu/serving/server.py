"""Stdlib HTTP front end for the serving subsystem.

A ``ThreadingHTTPServer`` (one handler thread per connection — the handler
threads only parse JSON and block on futures; all device work stays on the
single batcher worker) exposing:

- ``POST /predict`` — body ``{"inputs": {name: nested-list}, "timeout_ms":
  optional}`` (or inputs as a list in feed order). Reply ``{"outputs":
  {fetch_name: nested-list}, "rows": n, "latency_ms": ...}``. Typed errors
  map to status codes: InvalidRequest→400, Overloaded→429 (backpressure —
  clients retry with backoff), DeadlineExceeded→504, EngineClosed→503,
  anything else→500. Every error body is ``{"error": type, "message": ...}``.
- ``GET /healthz`` — 200 ``{"status": "ok"}`` while serving, 503
  ``{"status": "draining"}`` once shutdown begins (load-balancer eviction).
- ``GET /metrics`` — Prometheus text exposition from the shared
  observability registry (serving_* series plus anything telemetry
  collected).

Run one from the CLI::

    python -m paddle_tpu.serving.server --model-dir /path/to/model \
        --port 8080 --max-batch-size 16 --batch-timeout-ms 2

Shutdown (SIGINT / :meth:`ServingServer.shutdown`) is graceful: healthz
flips to draining, the batcher drains every admitted request, then the
listener stops.
"""
from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from . import metrics as _m
from .batcher import (DEFAULT_BATCH_TIMEOUT_MS, DEFAULT_QUEUE_DEPTH,
                      MicroBatcher)
from .engine import InferenceEngine
from .errors import (DeadlineExceeded, EngineClosed, EngineUnhealthy,
                     InvalidRequest, Overloaded)
from ..log_helper import get_logger
from ..observability import TraceContext
from ..observability import distributed as _dobs

__all__ = ['ServingServer', 'create_server']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [serving] %(message)s')

MAX_BODY_BYTES = 64 * 1024 * 1024

_STATUS_BY_ERROR = ((InvalidRequest, 400), (Overloaded, 429),
                    (DeadlineExceeded, 504), (EngineUnhealthy, 503),
                    (EngineClosed, 503))

# /generate request schema: unknown keys are a 400 naming the field (a
# typo'd sampling knob silently dropped would serve greedy while the
# client believes it set temperature)
_SAMPLING_KEYS = frozenset(('temperature', 'top_k', 'top_p', 'seed'))
_GENERATE_KEYS = frozenset(('prompt', 'max_new_tokens', 'eos_id', 'stream',
                            'timeout_ms', 'request_id')) | _SAMPLING_KEYS


class _Handler(BaseHTTPRequestHandler):
    protocol_version = 'HTTP/1.1'
    server_version = 'paddle-tpu-serving'

    # BaseHTTPRequestHandler writes access logs to stderr with print-style
    # formatting; route through log_helper instead (never print)
    def log_message(self, fmt, *args):
        _logger.debug('%s %s', self.address_string(), fmt % args)

    def _reply(self, code, body, content_type='application/json'):
        data = body if isinstance(body, bytes) else json.dumps(body).encode()
        self.send_response(code)
        self.send_header('Content-Type', content_type)
        self.send_header('Content-Length', str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass                      # client went away; nothing to salvage
        _m.http_responses.labels(code=code).inc()

    def _error(self, code, exc):
        self._reply(code, {'error': type(exc).__name__, 'message': str(exc)})

    def do_GET(self):
        srv = self.server.serving
        if self.path == '/healthz':
            # unix_time rides every healthz reply: the router's poll uses
            # it for the clock-offset handshake that aligns this process's
            # trace spans onto the router's timeline (trace_merge.py)
            if srv.draining:
                self._reply(503, {'status': 'draining',
                                  'unix_time': time.time()})
            elif srv.breaker_states():
                # a tripped (or probing) circuit breaker: this replica is
                # alive but should not receive traffic — 503 'degraded'
                # evicts it from the balancer until the probe closes the
                # breaker again (docs/SERVING.md "Circuit breaker")
                self._reply(503, {'status': 'degraded',
                                  'breakers': srv.breaker_states(),
                                  'unix_time': time.time()})
            else:
                body = {'status': 'ok', 'replica': srv.replica_id,
                        'warmup': srv.warmup_status(),
                        'unix_time': time.time()}
                if srv.engine is not None:
                    body['buckets'] = srv.engine.buckets
                    body['compiled'] = srv.engine.compiled_buckets
                if srv.generator is not None:
                    # the always-on windowed load series ride every
                    # healthz reply: the router caches them per replica
                    # and the elastic autoscaler reads queue_depth /
                    # occupancy / ttft p99 off that cache — no second
                    # scrape channel (docs/SERVING.md "Autoscaler")
                    body['series'] = {
                        name: _dobs.series(name).snapshot()
                        for name in ('queue_depth', 'occupancy', 'ttft')}
                    eng = srv.generator.engine
                    body['decode'] = {
                        'slots': eng.slots,
                        'active': srv.generator.active(),
                        'waiting': srv.generator.pending(),
                        'cache_blocks_used': eng.pool.allocator.used,
                        'cache_blocks_total': eng.pool.allocator.capacity,
                        'prompt_buckets': eng.prompt_buckets,
                    }
                slo = srv.slo_status()
                if slo is not None:
                    body['slo'] = slo
                self._reply(200, body)
        elif self.path == '/metrics':
            from ..observability import registry
            self._reply(200, registry.prometheus_text().encode(),
                        content_type='text/plain; version=0.0.4')
        else:
            self._reply(404, {'error': 'NotFound', 'message': self.path})

    def _read_json_body(self):
        """Parse the request body; returns the payload dict or None after
        replying with the 4xx itself."""
        try:
            length = int(self.headers.get('Content-Length') or 0)
        except ValueError:
            length = -1
        if length <= 0:
            self._error(400, InvalidRequest('missing request body'))
            return None
        if length > MAX_BODY_BYTES:
            self._error(413, InvalidRequest(
                f'body of {length} bytes exceeds {MAX_BODY_BYTES}'))
            return None
        try:
            payload = json.loads(self.rfile.read(length))
        except (ValueError, UnicodeDecodeError) as e:
            self._error(400, InvalidRequest(f'bad JSON body: {e}'))
            return None
        if not isinstance(payload, dict):
            self._error(400, InvalidRequest('body must be a JSON object'))
            return None
        return payload

    def _write_chunk(self, obj):
        """One chunked-transfer NDJSON line."""
        data = json.dumps(obj).encode() + b'\n'
        self.wfile.write(b'%x\r\n' % len(data) + data + b'\r\n')
        self.wfile.flush()

    def do_POST(self):
        if self.path == '/generate':
            return self._do_generate()
        if self.path != '/predict':
            return self._reply(404, {'error': 'NotFound',
                                     'message': self.path})
        srv = self.server.serving
        if srv.batcher is None:
            return self._reply(404, {
                'error': 'NotFound',
                'message': 'no predict engine configured (decode-only '
                           'server; use POST /generate)'})
        try:
            length = int(self.headers.get('Content-Length') or 0)
        except ValueError:
            length = -1
        if length <= 0:
            return self._error(400, InvalidRequest('missing request body'))
        if length > MAX_BODY_BYTES:
            return self._error(413, InvalidRequest(
                f'body of {length} bytes exceeds {MAX_BODY_BYTES}'))
        try:
            payload = json.loads(self.rfile.read(length))
        except (ValueError, UnicodeDecodeError) as e:
            return self._error(400, InvalidRequest(f'bad JSON body: {e}'))
        if not isinstance(payload, dict) or 'inputs' not in payload:
            return self._error(400, InvalidRequest(
                'body must be {"inputs": {...}, "timeout_ms": optional}'))
        timeout_ms = payload.get('timeout_ms')
        if timeout_ms is not None and not isinstance(timeout_ms, (int, float)):
            return self._error(400, InvalidRequest(
                f'timeout_ms must be a number, got {timeout_ms!r}'))
        t0 = time.perf_counter()
        try:
            fut = srv.batcher.submit(payload['inputs'], timeout_ms)
            outs = fut.result(srv.request_timeout)
        except tuple(e for e, _ in _STATUS_BY_ERROR) as e:
            for etype, code in _STATUS_BY_ERROR:
                if isinstance(e, etype):
                    return self._error(code, e)
        except TimeoutError as e:
            return self._error(504, e)
        except Exception as e:     # engine/internal failure: a 500, not a hang
            _logger.error('predict failed: %s: %s', type(e).__name__, e)
            return self._error(500, e)
        names = srv.engine.get_output_names()
        self._reply(200, {
            'outputs': {n: np.asarray(o).tolist() for n, o in
                        zip(names, outs)},
            'rows': int(np.asarray(outs[0]).shape[0]) if outs else 0,
            'latency_ms': round((time.perf_counter() - t0) * 1e3, 3)})

    def _do_generate(self):
        """POST /generate — stateful streaming generation (docs/SERVING.md
        "Stateful decode"). Body::

            {"prompt": [token ids], "max_new_tokens": 16,
             "eos_id": optional, "stream": true, "timeout_ms": optional,
             "temperature": 0.0, "top_k": 0, "top_p": 1.0,
             "seed": optional, "request_id": optional}

        Sampling keys are validated typed (serving/decode/sampling.py):
        a bad value OR an unknown body key is a 400 naming the field —
        a typo'd knob must never be silently dropped. Sampled streams
        replay bitwise from ``request_id`` (or ``seed``); greedy
        (temperature 0, the default) is exact argmax.

        ``stream=true`` (default) replies 200 with chunked NDJSON: one
        ``{"token": id, "index": i}`` line per decoded token, then a final
        ``{"done": true, "finish_reason": ..., "tokens": [...],
        "latency_ms": ...}`` line. A failure after streaming began arrives
        as an ``{"error": ..., "message": ...}`` line (the 200 status is
        already on the wire — chunked streaming's standard caveat).
        ``stream=false`` blocks and returns the whole generation as one
        JSON reply. Pre-admission failures map like /predict:
        InvalidRequest→400, Overloaded→429, DeadlineExceeded→504,
        EngineClosed→503."""
        srv = self.server.serving
        if srv.generator is None:
            return self._reply(404, {
                'error': 'NotFound',
                'message': 'no decode engine configured (predict-only '
                           'server; use POST /predict)'})
        payload = self._read_json_body()
        if payload is None:
            return
        prompt = payload.get('prompt')
        if not isinstance(prompt, list):
            return self._error(400, InvalidRequest(
                'body must include "prompt": [token ids]'))
        unknown = sorted(set(payload) - _GENERATE_KEYS)
        if unknown:
            return self._error(400, InvalidRequest(
                f'unknown request field(s): {", ".join(unknown)}; '
                f'supported: {", ".join(sorted(_GENERATE_KEYS))}'))
        sampling = {k: payload[k] for k in _SAMPLING_KEYS if k in payload}
        try:
            # distributed trace carrier (docs/OBSERVABILITY.md): absent
            # header = untraced (one dict get); malformed = client bug, 400
            trace = TraceContext.from_headers(self.headers)
        except ValueError as e:
            return self._error(400, InvalidRequest(str(e)))
        t0 = time.perf_counter()
        try:
            stream = srv.generator.submit(
                prompt,
                max_new_tokens=payload.get('max_new_tokens', 16),
                eos_id=payload.get('eos_id'),
                timeout_ms=payload.get('timeout_ms'),
                sampling=sampling or None,
                request_id=payload.get('request_id'),
                trace=trace)
        except tuple(e for e, _ in _STATUS_BY_ERROR) as e:
            for etype, code in _STATUS_BY_ERROR:
                if isinstance(e, etype):
                    return self._error(code, e)
        except Exception as e:
            _logger.error('generate failed: %s: %s', type(e).__name__, e)
            return self._error(500, e)

        if payload.get('stream', True) is False:
            try:
                toks = stream.result(srv.request_timeout)
            except tuple(e for e, _ in _STATUS_BY_ERROR) as e:
                for etype, code in _STATUS_BY_ERROR:
                    if isinstance(e, etype):
                        return self._error(code, e)
            except TimeoutError as e:
                return self._error(504, e)
            except Exception as e:
                _logger.error('generate failed: %s: %s',
                              type(e).__name__, e)
                return self._error(500, e)
            return self._reply(200, {
                'tokens': toks, 'finish_reason': stream.finish_reason,
                'latency_ms': round((time.perf_counter() - t0) * 1e3, 3),
                **stream.meta})

        # chunked per-token streaming
        self.send_response(200)
        self.send_header('Content-Type', 'application/x-ndjson')
        self.send_header('Transfer-Encoding', 'chunked')
        self.end_headers()
        try:
            try:
                for i, tok in enumerate(
                        stream.iter_tokens(srv.request_timeout)):
                    self._write_chunk({'token': int(tok), 'index': i})
                self._write_chunk({
                    'done': True, 'finish_reason': stream.finish_reason,
                    'tokens': stream.tokens,
                    'latency_ms': round((time.perf_counter() - t0) * 1e3,
                                        3),
                    **stream.meta})
            except (BrokenPipeError, ConnectionResetError):
                raise                 # client went away: just stop
            except Exception as e:    # failure mid-stream: error line
                self._write_chunk({'error': type(e).__name__,
                                   'message': str(e)})
            self.wfile.write(b'0\r\n\r\n')
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass                      # generation continues server-side
        _m.http_responses.labels(code=200).inc()


class ServingServer:
    """Engine + batcher + ThreadingHTTPServer, wired and lifecycle-managed.

    Pass an :class:`InferenceEngine` (or a model dir, from which one is
    built). ``port=0`` binds an ephemeral port (tests); read ``.port`` after
    construction.
    """

    def __init__(self, engine, host='127.0.0.1', port=8080,
                 max_batch_size=None, batch_timeout_ms=None, queue_depth=None,
                 default_timeout_ms=None, request_timeout=60.0, warmup=False,
                 generator=None):
        """``generator``: an optional :class:`decode.DecodeScheduler` —
        enables ``POST /generate`` streaming generation beside (or, with
        ``engine=None``, instead of) the stateless ``/predict`` path."""
        if engine is None:
            if generator is None:
                raise ValueError('need an engine, a generator, or both')
            self.engine = None
            self.batcher = None
        else:
            if not isinstance(engine, InferenceEngine):
                engine = InferenceEngine(engine,
                                         max_batch_size=max_batch_size)
            self.engine = engine
            if warmup:
                timings = self.engine.warmup()
                _logger.info('warmed %d buckets: %s', len(timings),
                             {b: round(s, 3) for b, s in timings.items()})
            self.batcher = MicroBatcher(
                engine,
                max_batch_size=max_batch_size,
                batch_timeout_ms=(DEFAULT_BATCH_TIMEOUT_MS
                                  if batch_timeout_ms is None
                                  else batch_timeout_ms),
                queue_depth=(DEFAULT_QUEUE_DEPTH if queue_depth is None
                             else queue_depth),
                default_timeout_ms=default_timeout_ms)
        self.generator = generator
        if generator is not None and warmup:
            timings = generator.engine.warmup()
            _logger.info('warmed decode engine: %s',
                         {k: round(s, 3) for k, s in timings.items()})
        self.request_timeout = request_timeout
        # PADDLE_TPU_SLO monitor (strict parse fails construction, not the
        # first /healthz) + span-record process label for trace merging
        self._slo = _dobs.SLOMonitor.from_env()
        _dobs.set_process_label(self.replica_id)
        self.draining = False
        self._shutdown_started = False
        self._shutdown_lock = threading.Lock()
        self._old_handlers = {}
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.serving = self
        self._thread = None

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def replica_id(self):
        """This serving process's identity (stamped into /healthz and every
        GenerationStream's metadata)."""
        if self.generator is not None:
            return self.generator.replica_id
        return (os.environ.get('PADDLE_TPU_REPLICA_ID')
                or f'replica-{os.getpid()}')

    def warmup_status(self):
        """Per-component compile-warmth for /healthz: the serving-tier
        router refuses to route to a replica whose ``done`` is false, so a
        restart never serves its first requests into a compile cliff.
        ``done`` = every configured component (predict bucket ladder,
        decode prefill ladder + lockstep step shape) is precompiled."""
        status = {}
        if self.engine is not None:
            status['predict'] = self.engine.warmed
        if self.generator is not None:
            status['decode'] = self.generator.engine.warmed
        status['done'] = all(status.values()) if status else False
        return status

    def slo_status(self):
        """Evaluate the PADDLE_TPU_SLO clauses against the live windowed
        series (None when no SLO is configured). Each evaluation also
        drives the slo_ok gauges + slo_breaches burn counters."""
        if self._slo is None:
            return None
        return self._slo.evaluate()

    def breaker_states(self):
        """{component: breaker state} for every NON-closed circuit breaker
        (empty dict = fully healthy)."""
        states = {}
        if self.batcher is not None and \
                self.batcher.breaker.state != 'closed':
            states['predict'] = self.batcher.breaker.state
        if self.generator is not None:
            breaker = getattr(self.generator, 'breaker', None)
            if breaker is not None and breaker.state != 'closed':
                states['decode'] = breaker.state
        return states

    def start(self):
        """Serve in a background thread; returns self."""
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name='paddle-tpu-serving-http',
                                        daemon=True)
        self._thread.start()
        _logger.info('serving on %s:%d (buckets %s)',
                     self._httpd.server_address[0], self.port,
                     self.engine.buckets if self.engine else '[decode-only]')
        return self

    def serve_forever(self):
        """Foreground serve (the CLI path). SIGTERM (pod preemption) and
        SIGINT (Ctrl-C) both trigger the graceful, timeout-capped drain —
        see :meth:`install_signal_handlers`."""
        _logger.info('serving on %s:%d (buckets %s)',
                     self._httpd.server_address[0], self.port,
                     self.engine.buckets if self.engine else '[decode-only]')
        try:
            self.install_signal_handlers()
        except ValueError:
            pass                       # not the main thread: Ctrl-C only
        try:
            self._httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            self.uninstall_signal_handlers()
            self.shutdown()

    def install_signal_handlers(self, signals=(signal.SIGTERM, signal.SIGINT)):
        """SIGTERM-safe shutdown (docs/RESILIENCE.md): on signal, /healthz
        flips to draining immediately (load-balancer eviction) and a
        background thread runs the graceful ``shutdown(drain=True)`` — the
        handler itself returns right away (signal context must stay cheap).
        The drain is capped by ``PADDLE_TPU_DRAIN_TIMEOUT_S`` (default 30);
        past the cap, remaining queued work fails fast with EngineClosed
        rather than holding the pod through its kill grace period.

        Must be called from the main thread; returns self. The CLI path
        (`serve_forever`) installs these automatically."""
        self._old_handlers = {}
        for s in signals:
            self._old_handlers[s] = signal.signal(s, self._on_signal)
        return self

    def uninstall_signal_handlers(self):
        for s, old in getattr(self, '_old_handlers', {}).items():
            try:
                signal.signal(s, old)
            except (ValueError, TypeError):
                pass
        self._old_handlers = {}

    def _on_signal(self, signum, frame):
        _logger.warning('signal %d: draining (healthz now 503)', signum)
        self.draining = True           # visible to /healthz immediately
        threading.Thread(target=self.shutdown, kwargs={'drain': True},
                         name='paddle-tpu-serving-drain',
                         daemon=True).start()

    def shutdown(self, drain=True, timeout=None):
        """Graceful stop: healthz flips to draining, admission closes, queued
        requests run to completion (drain=True), then the listener stops.
        `timeout` (default ``PADDLE_TPU_DRAIN_TIMEOUT_S``, 30s) caps the
        drain: components still busy at the deadline are re-closed with
        drain=False, failing their remaining queue fast."""
        with self._shutdown_lock:
            if self._shutdown_started:
                return
            self._shutdown_started = True
        self.draining = True
        if timeout is None:
            timeout = float(
                os.environ.get('PADDLE_TPU_DRAIN_TIMEOUT_S', '') or 30.0)
        deadline = time.monotonic() + timeout
        for comp in (self.batcher, self.generator):
            if comp is None:
                continue
            remaining = max(0.0, deadline - time.monotonic())
            comp.close(drain=drain, timeout=remaining if drain else None)
            if comp._worker.is_alive():
                # drain exceeded its budget: escalate to fail-fast so the
                # process exits inside the kill grace period
                _logger.warning(
                    'drain timeout (%.1fs) exceeded; failing remaining '
                    'queued work fast', timeout)
                comp.close(drain=False, timeout=5)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(5)
        _logger.info('serving stopped (drained=%s)', drain)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()


def create_server(model_dir_or_config, **kwargs):
    """One-call constructor: ``create_server('/path', port=8080).start()``."""
    return ServingServer(model_dir_or_config, **kwargs)


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description='paddle_tpu serving: micro-batched inference over HTTP')
    ap.add_argument('--model-dir', required=True)
    ap.add_argument('--model-filename', default=None)
    ap.add_argument('--params-filename', default=None)
    ap.add_argument('--host', default='0.0.0.0')
    ap.add_argument('--port', type=int, default=8080)
    ap.add_argument('--max-batch-size', type=int, default=None)
    ap.add_argument('--batch-timeout-ms', type=float, default=None)
    ap.add_argument('--queue-depth', type=int, default=None)
    ap.add_argument('--default-timeout-ms', type=float, default=None)
    ap.add_argument('--buckets', default=None,
                    help='comma-separated ladder, e.g. 1,2,4,8,16')
    ap.add_argument('--bf16', action='store_true')
    ap.add_argument('--no-warmup', action='store_true',
                    help='skip precompiling the bucket ladder at startup')
    args = ap.parse_args(argv)

    from ..inference import Config
    cfg = Config(args.model_dir, args.model_filename, args.params_filename)
    if args.bf16:
        cfg.enable_bf16()
    buckets = [int(b) for b in args.buckets.split(',')] if args.buckets \
        else None
    engine = InferenceEngine(cfg, max_batch_size=args.max_batch_size,
                             buckets=buckets)
    ServingServer(engine, host=args.host, port=args.port,
                  max_batch_size=args.max_batch_size,
                  batch_timeout_ms=args.batch_timeout_ms,
                  queue_depth=args.queue_depth,
                  default_timeout_ms=args.default_timeout_ms,
                  warmup=not args.no_warmup).serve_forever()


if __name__ == '__main__':
    main()
