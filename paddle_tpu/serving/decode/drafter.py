"""Draft-token proposers for speculative decoding.

A drafter guesses the next n tokens of a greedy stream CHEAPLY; the target
model verifies all of them in ONE batched (S, k) step (engine.spec_step)
and keeps the longest matching prefix. Wrong guesses cost nothing but the
lane they rode in — correctness never depends on the drafter, so the
interface is deliberately tiny::

    drafter.propose(history, n) -> list of <= n draft token ids

``history`` is the request's prompt + every token emitted so far — its
LAST element is the pending (emitted-but-uncached) token the drafts must
continue from.

Two implementations (``PADDLE_TPU_SPEC_DRAFTER`` picks one; docs/SERVING.md
"Sampling & speculative decode"):

- :class:`NGramDrafter` — zero extra weights: find the most recent earlier
  occurrence of the history's longest-matching suffix n-gram and propose
  the tokens that followed it (prompt-copy / repetition capture). This is
  the default, and on repetitive or prompt-grounded traffic it is hard to
  beat per dollar.
- :class:`DraftModelDrafter` — a small TransformerLM greedy-decoded at ONE
  fixed padded shape (models/causal_lm.greedy_generate's single-compile
  discipline, sharing the engine's ``padded_context``), for workloads with
  no surface repetition.
"""
from __future__ import annotations

from ..errors import InvalidRequest

__all__ = ['NGramDrafter', 'DraftModelDrafter', 'build_drafter',
           'DRAFTER_CHOICES']

DRAFTER_CHOICES = ('ngram', 'draft_model', 'off')


class NGramDrafter:
    """Suffix-match drafter: longest n-gram first (``max_ngram`` down to
    ``min_ngram``), most recent earlier occurrence wins. O(L·g) per probe
    over the request's own short history — microseconds next to a model
    step."""

    def __init__(self, max_ngram=3, min_ngram=1):
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, history, n):
        history = list(history)
        n = int(n)
        if n <= 0 or len(history) < self.min_ngram + 1:
            return []
        top = min(self.max_ngram, len(history) - 1)
        for g in range(top, self.min_ngram - 1, -1):
            suffix = history[-g:]
            # scan right-to-left: the MOST RECENT earlier occurrence is the
            # best predictor of what follows now
            for i in range(len(history) - g - 1, -1, -1):
                if history[i:i + g] == suffix:
                    cont = history[i + g:i + g + n]
                    if cont:
                        return cont
        return []


class DraftModelDrafter:
    """Greedy continuation from a small draft LM at one fixed padded shape.

    ``pad_len`` should be the target engine's ``padded_context`` so the
    draft model compiles exactly once and its positions line up with the
    stream it drafts for. Proposals are clamped so prompt + drafts never
    exceed the pad (the verify step re-checks budgets anyway)."""

    def __init__(self, model, pad_len):
        if hasattr(model, 'eval'):
            model.eval()
        self.model = model
        self.pad_len = int(pad_len)

    def propose(self, history, n):
        from ...models.causal_lm import greedy_generate
        history = [int(t) for t in history]
        n = min(int(n), self.pad_len - len(history))
        if n <= 0 or not history:
            return []
        return greedy_generate(self.model, history, n,
                               pad_len=self.pad_len)


def build_drafter(choice, pad_len, draft_model=None):
    """Resolve a drafter name (the ``PADDLE_TPU_SPEC_DRAFTER`` knob /
    scheduler arg) into an instance. 'off' → None (speculative rounds run
    with zero drafts — the k-window still batches suffix prefill)."""
    if choice is None or isinstance(choice, str):
        name = (choice or 'ngram').strip()
        if name not in DRAFTER_CHOICES:
            raise InvalidRequest(
                f'drafter {name!r} is not supported; supported values: '
                f'{", ".join(DRAFTER_CHOICES)}')
        if name == 'off':
            return None
        if name == 'ngram':
            return NGramDrafter()
        if draft_model is None:
            from ...models.causal_lm import CausalLMConfig, TransformerLM
            draft_model = TransformerLM(CausalLMConfig.tiny())
        return DraftModelDrafter(draft_model, pad_len)
    return choice                     # duck-typed: anything with .propose
