"""DecodeEngine: phase-split stateful generation over a paged KV cache.

The compile-count story (the whole point — models/transformer.py's original
decode re-compiled per generated length):

- **prefill** runs the prompt once at a bucket-ladder shape (reusing
  serving.engine.bucket_ladder — powers of two up to ``max_prompt_len``),
  writing its K/V into cache blocks: ≤ ``len(prompt_buckets)`` compiles,
  ever.
- **decode** steps all S slots in lockstep at ONE fixed shape
  ((S, 1) tokens + (S, max_blocks_per_seq) tables + (S,) context lengths):
  exactly one compile, regardless of how long any sequence runs.

tests/framework/test_decode_engine.py asserts both bounds through the eager
kernel-cache counters.

Bitwise contract (CPU): each decode step's logits row equals the matching
row of an uncached whole-sequence forward padded to ``padded_context`` —
see ops/nn_ops.py:paged_attention and models/causal_lm.py for why the
extent and the matmul formulation matter. ``check_parity`` in the tests and
tools/bench_decode.py asserts it per request.

The engine is single-threaded by design (one scheduler worker owns it);
it holds no queueing or lifecycle logic — that is scheduler.py.
"""
from __future__ import annotations

import contextlib
import time

import numpy as np

from .. import metrics as _m
from ..engine import bucket_ladder
from ..errors import InvalidRequest
from .kv_cache import (CacheContext, KVCachePool, DEFAULT_BLOCK_SIZE,
                       DEFAULT_MAX_BLOCKS, DEFAULT_SLOTS)

__all__ = ['DecodeEngine']

_NULL_LOCK = contextlib.nullcontext()


class DecodeEngine:
    """Stateful generation over ``model`` (anything with the
    models/causal_lm.py forward contract: ``model(ids, pos_ids=None,
    cache=None) -> logits``; attention layers must route ``cache=`` into
    MultiHeadAttention).

    - ``slots``: fixed lockstep decode batch size S.
    - ``block_size`` / ``max_blocks``: KV-cache pool geometry.
    - ``max_prompt_len``: top rung of the prefill bucket ladder.
    - ``max_new_tokens_cap``: per-request generation budget cap (block
      reservations are taken against prompt + budget at admission, so the
      cap bounds what one request can strand).
    """

    def __init__(self, model, slots=None, block_size=None, max_blocks=None,
                 max_prompt_len=64, max_new_tokens_cap=64,
                 prompt_buckets=None, eos_id=None, prefix_cache=None,
                 model_lock=None):
        self.model = model
        if hasattr(model, 'eval'):
            model.eval()           # generation is inference: no dropout
        # colocated disaggregation (serving/tier/disagg.py) runs a prefill
        # engine's forwards on a worker thread beside this engine's decode
        # steps; a shared lock serializes the two MODEL calls (the dygraph
        # tape's no_grad flag is process-global). None = zero overhead.
        self._model_lock = model_lock
        self.slots = int(slots or DEFAULT_SLOTS)
        self.max_prompt_len = int(max_prompt_len)
        self.max_new_tokens_cap = int(max_new_tokens_cap)
        self.eos_id = eos_id
        self.prompt_buckets = bucket_ladder(self.max_prompt_len,
                                            prompt_buckets)
        block_size = int(block_size or DEFAULT_BLOCK_SIZE)
        max_total = self.max_prompt_len + self.max_new_tokens_cap
        max_bps = -(-max_total // block_size)
        self.pool = KVCachePool(block_size=block_size,
                                num_blocks=max_blocks or DEFAULT_MAX_BLOCKS,
                                max_blocks_per_seq=max_bps)
        if self.pool.allocator.capacity < max_bps:
            # an empty pool must always cover one maximal request, or the
            # scheduler's FIFO head could wait forever
            raise ValueError(
                f'max_blocks={self.pool.num_blocks} cannot hold one '
                f'maximal request ({max_bps} blocks for '
                f'{max_total} tokens at block_size={block_size})')
        _m.decode_slots_total.set(self.slots)
        _m.decode_cache_blocks_total.set(self.pool.allocator.capacity)
        self._prefill_compiled = set()
        self._step_compiled = False
        # radix prefix cache (serving/tier/prefix_cache.py): arg wins, else
        # the strict-parsed PADDLE_TPU_PREFIX_CACHE env knob (default off)
        from ..tier.knobs import ENV_PREFIX_CACHE, parse_flag_env
        if prefix_cache is None:
            prefix_cache = parse_flag_env(ENV_PREFIX_CACHE, default=False)
        if prefix_cache is False:
            self.prefix_cache = None
        elif prefix_cache is True:
            from ..tier.prefix_cache import PrefixCache
            self.prefix_cache = PrefixCache(self.pool)
        else:
            self.prefix_cache = prefix_cache

    # -- geometry ----------------------------------------------------------
    @property
    def block_size(self):
        return self.pool.block_size

    @property
    def padded_context(self):
        """The key extent every attention read pads to — run the uncached
        reference (models/causal_lm.greedy_generate) at this pad_len for
        bitwise-identical tokens."""
        return self.pool.padded_context

    def validate(self, prompt_ids, max_new_tokens):
        """Typed admission checks; returns (prompt list, max_new int)."""
        try:
            prompt = [int(t) for t in prompt_ids]
        except (TypeError, ValueError) as e:
            raise InvalidRequest(f'prompt must be a sequence of ints: {e}')
        if not prompt:
            raise InvalidRequest('empty prompt')
        if len(prompt) > self.max_prompt_len:
            raise InvalidRequest(
                f'prompt of {len(prompt)} tokens exceeds max_prompt_len='
                f'{self.max_prompt_len}')
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise InvalidRequest(f'max_new_tokens must be >= 1, got '
                                 f'{max_new}')
        if max_new > self.max_new_tokens_cap:
            raise InvalidRequest(
                f'max_new_tokens={max_new} exceeds the engine cap '
                f'{self.max_new_tokens_cap}')
        return prompt, max_new

    def reserve_table(self, prompt_len, max_new_tokens, prompt=None):
        """Block reservation for prompt + budget (raises OutOfBlocks — the
        scheduler treats that as 'wait for a finishing slot'). With the
        prefix cache enabled and ``prompt`` given, the table's front blocks
        are shared cached-prefix blocks (``table.cached_len`` > 0) and only
        the remainder is freshly allocated."""
        total = int(prompt_len) + int(max_new_tokens)
        if self.prefix_cache is not None:
            return self.prefix_cache.acquire_table(prompt or [], total)
        return self.pool.new_table(total)

    def publish_prefix(self, prompt, table):
        """Publish ``table``'s whole-prompt blocks into the prefix cache
        (no-op when the cache is off). The scheduler calls this once the
        full prompt's K/V is cached — after a cold prefill, a suffix fill,
        or a disaggregated injection."""
        if self.prefix_cache is not None:
            self.prefix_cache.insert(prompt, table)

    def release_table(self, table):
        self.pool.free_table(table)
        _m.decode_cache_blocks_used.set(self.pool.allocator.used)

    # -- phases ------------------------------------------------------------
    def prefill(self, prompt, table):
        """Run the bucket-padded prompt once, writing K/V into ``table``'s
        blocks, and return the FIRST generated token (greedy). Sets
        ``table.context_len = len(prompt)``."""
        from ...dygraph.tape import Tensor, no_grad_guard
        P = len(prompt)
        bucket = next(b for b in self.prompt_buckets if P <= b)
        ids = np.zeros((1, bucket), np.int64)
        ids[0, :P] = prompt
        table.context_len = P
        ctx = CacheContext(self.pool, 'prefill', [table])
        t0 = time.perf_counter()
        with self._model_lock or _NULL_LOCK:
            with no_grad_guard():
                logits = self.model(Tensor(ids, stop_gradient=True),
                                    cache=ctx)
                row = np.asarray(logits.numpy())[0, P - 1]
        dt = time.perf_counter() - t0
        _m.decode_prefill_seconds.observe(dt)
        if bucket not in self._prefill_compiled:
            self._prefill_compiled.add(bucket)
            _m.decode_prefill_compiles.inc()
        _m.decode_cache_blocks_used.set(self.pool.allocator.used)
        return int(row.argmax())

    def decode_step(self, tokens, tables):
        """One lockstep step over all S slots at fixed shape.

        ``tokens``: length-S list, the token to feed per slot (None =
        inactive). ``tables``: length-S list of BlockTables (None =
        inactive). For an active slot with context c, the fed token is the
        one at position c (it was sampled from the previous step/prefill
        but not yet cached); its K/V are written and attended this step.
        Returns (S,) next-token ids (greedy; garbage on inactive slots) and
        advances each active table's context_len by 1."""
        from ...dygraph.tape import Tensor, no_grad_guard
        S = self.slots
        assert len(tokens) == S and len(tables) == S
        ids = np.zeros((S, 1), np.int64)
        pos = np.zeros((S, 1), np.int64)
        ctx_lens = []
        for s in range(S):
            if tables[s] is None:
                ctx_lens.append(1)          # scratch read, masked + ignored
                continue
            c = tables[s].context_len
            ids[s, 0] = tokens[s]
            pos[s, 0] = c
            tables[s].context_len = c + 1   # the fed token becomes cached
            ctx_lens.append(c + 1)
        ctx = CacheContext(self.pool, 'decode', tables, ctx_lens)
        t0 = time.perf_counter()
        with self._model_lock or _NULL_LOCK:
            with no_grad_guard():
                logits = self.model(Tensor(ids, stop_gradient=True),
                                    pos_ids=Tensor(pos, stop_gradient=True),
                                    cache=ctx)
                out = np.asarray(logits.numpy())[:, 0].argmax(-1)
        dt = time.perf_counter() - t0
        self._step_compiled = True
        _m.decode_step_seconds.observe(dt)
        _m.decode_steps.inc()
        active = sum(t is not None for t in tables)
        _m.decode_slots_active.set(active)
        _m.decode_slot_occupancy.observe(active / max(S, 1))
        return out

    def inject_prefill(self, table, payload):
        """Receive a disaggregated prefill (serving/tier/disagg.py): write
        the payload's whole K/V blocks into ``table``'s first blocks of
        THIS pool and mark the prompt cached. Returns the payload's first
        greedy token. ``table.cached_len`` blocks at the front (shared
        prefix-cache blocks) are already filled and are skipped."""
        bs = self.pool.block_size
        if payload.block_size != bs:
            raise InvalidRequest(
                f'handoff block_size {payload.block_size} != engine '
                f'block_size {bs}')
        skip = table.cached_len // bs          # shared blocks already filled
        nb = payload.num_blocks
        if nb > len(table.blocks):
            raise InvalidRequest(
                f'handoff carries {nb} blocks but the table reserves only '
                f'{len(table.blocks)}')
        for layer, (k, v) in enumerate(payload.layers):
            if skip:
                k, v = k[:, skip:], v[:, skip:]
            if k.shape[1]:
                self.pool.write_whole_blocks(
                    layer, table.blocks[skip:nb], k, v)
        table.context_len = payload.context_len
        _m.decode_cache_blocks_used.set(self.pool.allocator.used)
        return int(payload.first_token)

    # -- warmup ------------------------------------------------------------
    @property
    def warmed(self):
        """True once the whole prefill bucket ladder AND the lockstep
        decode-step shape have compiled (via :meth:`warmup` or organic
        traffic). Surfaced through ``/healthz`` so the serving-tier router
        never sends traffic into a cold replica's compile cliff."""
        return (self._step_compiled
                and all(b in self._prefill_compiled
                        for b in self.prompt_buckets))

    def warmup(self):
        """Precompile the prefill ladder + the decode-step shape before
        traffic arrives (same contract as InferenceEngine.warmup). Returns
        {phase: seconds}. Uses temporary blocks; the pool ends unchanged."""
        timings = {}
        for bucket in self.prompt_buckets:
            table = self.reserve_table(bucket, 1)
            t0 = time.perf_counter()
            tok = self.prefill([1] * bucket, table)
            timings[f'prefill_{bucket}'] = time.perf_counter() - t0
            # one decode step over slot 0 also warms the step shape
            tokens = [tok] + [None] * (self.slots - 1)
            tables = [table] + [None] * (self.slots - 1)
            t0 = time.perf_counter()
            self.decode_step(tokens, tables)
            timings.setdefault('decode_step',
                               time.perf_counter() - t0)
            self.release_table(table)
        return timings
