"""DecodeEngine: phase-split stateful generation over a paged KV cache.

The compile-count story (the whole point — models/transformer.py's original
decode re-compiled per generated length):

- **prefill** runs the prompt once at a bucket-ladder shape (reusing
  serving.engine.bucket_ladder — powers of two up to ``max_prompt_len``),
  writing its K/V into cache blocks: ≤ ``len(prompt_buckets)`` compiles,
  ever.
- **decode** steps all S slots in lockstep at ONE fixed shape
  ((S, 1) tokens + (S, max_blocks_per_seq) tables + (S,) context lengths):
  exactly one compile, regardless of how long any sequence runs.

tests/framework/test_decode_engine.py asserts both bounds through the eager
kernel-cache counters.

Bitwise contract (CPU): each decode step's logits row equals the matching
row of an uncached whole-sequence forward padded to ``padded_context`` —
see ops/nn_ops.py:paged_attention and models/causal_lm.py for why the
extent and the matmul formulation matter. ``check_parity`` in the tests and
tools/bench_decode.py asserts it per request.

The engine is single-threaded by design (one scheduler worker owns it);
it holds no queueing or lifecycle logic — that is scheduler.py.
"""
from __future__ import annotations

import contextlib
import time

import numpy as np

from .. import metrics as _m
from ...observability import distributed as _dobs
from ..engine import bucket_ladder
from ..errors import InvalidRequest
from .kv_cache import (CacheContext, KVCachePool, DEFAULT_BLOCK_SIZE,
                       DEFAULT_MAX_BLOCKS, DEFAULT_SLOTS)

__all__ = ['DecodeEngine']

_NULL_LOCK = contextlib.nullcontext()


class DecodeEngine:
    """Stateful generation over ``model`` (anything with the
    models/causal_lm.py forward contract: ``model(ids, pos_ids=None,
    cache=None) -> logits``; attention layers must route ``cache=`` into
    MultiHeadAttention).

    - ``slots``: fixed lockstep decode batch size S.
    - ``block_size`` / ``max_blocks``: KV-cache pool geometry.
    - ``max_prompt_len``: top rung of the prefill bucket ladder.
    - ``max_new_tokens_cap``: per-request generation budget cap (block
      reservations are taken against prompt + budget at admission, so the
      cap bounds what one request can strand).
    - ``spec_decode`` / ``spec_k``: speculative decoding (the batched
      (S, k) verify step — :meth:`spec_step`). Arg wins, else the
      ``PADDLE_TPU_SPEC_DECODE`` knob, default OFF; an explicit env ``0``
      is the hard escape hatch and wins even over ``spec_decode=True``
      (an operator must be able to disable speculation on a deployed
      binary without a code change).
    """

    def __init__(self, model, slots=None, block_size=None, max_blocks=None,
                 max_prompt_len=64, max_new_tokens_cap=64,
                 prompt_buckets=None, eos_id=None, prefix_cache=None,
                 model_lock=None, spec_decode=None, spec_k=None,
                 kv_dtype=None):
        self.model = model
        if hasattr(model, 'eval'):
            model.eval()           # generation is inference: no dropout
        # colocated disaggregation (serving/tier/disagg.py) runs a prefill
        # engine's forwards on a worker thread beside this engine's decode
        # steps; a shared lock serializes the two MODEL calls (the dygraph
        # tape's no_grad flag is process-global). None = zero overhead.
        self._model_lock = model_lock
        self.slots = int(slots or DEFAULT_SLOTS)
        self.max_prompt_len = int(max_prompt_len)
        self.max_new_tokens_cap = int(max_new_tokens_cap)
        self.eos_id = eos_id
        self.prompt_buckets = bucket_ladder(self.max_prompt_len,
                                            prompt_buckets)
        block_size = int(block_size or DEFAULT_BLOCK_SIZE)
        max_total = self.max_prompt_len + self.max_new_tokens_cap
        max_bps = -(-max_total // block_size)
        # KV storage dtype: arg wins, else the strict-parsed
        # PADDLE_TPU_KV_DTYPE knob (default f32 — the bitwise-exact path)
        from ..tier.knobs import (ENV_KV_DTYPE, KV_DTYPE_CHOICES,
                                  parse_choice_env)
        if kv_dtype is None:
            kv_dtype = parse_choice_env(ENV_KV_DTYPE, KV_DTYPE_CHOICES,
                                        'f32')
        num_blocks = self._resolve_num_blocks(model, max_blocks, block_size,
                                              max_bps, kv_dtype)
        self.pool = KVCachePool(block_size=block_size,
                                num_blocks=num_blocks,
                                max_blocks_per_seq=max_bps,
                                kv_dtype=kv_dtype)
        if self.pool.allocator.capacity < max_bps:
            # an empty pool must always cover one maximal request, or the
            # scheduler's FIFO head could wait forever
            raise ValueError(
                f'max_blocks={self.pool.num_blocks} cannot hold one '
                f'maximal request ({max_bps} blocks for '
                f'{max_total} tokens at block_size={block_size})')
        _m.decode_slots_total.set(self.slots)
        _m.decode_cache_blocks_total.set(self.pool.allocator.capacity)
        from .kv_cache import KV_DTYPE_CODES
        _m.kv_cache_dtype.set(KV_DTYPE_CODES[self.pool.kv_dtype])
        self._prefill_compiled = set()
        self._step_compiled = False
        self._spec_compiled = False
        # speculative decoding: env '0' is the hard escape hatch (wins over
        # the arg); otherwise arg wins, else env, default off
        from ..tier.knobs import (ENV_SPEC_DECODE, ENV_SPEC_K,
                                  parse_flag_env, parse_int_env)
        import os as _os
        env_raw = _os.environ.get(ENV_SPEC_DECODE, '').strip()
        if env_raw == '0':
            self.spec_enabled = False
        elif spec_decode is not None:
            self.spec_enabled = bool(spec_decode)
        else:
            self.spec_enabled = parse_flag_env(ENV_SPEC_DECODE,
                                               default=False)
        self.spec_k = int(spec_k if spec_k is not None
                          else parse_int_env(ENV_SPEC_K, 4, minimum=2))
        if self.spec_k < 2:
            raise ValueError(f'spec_k must be >= 2, got {self.spec_k}')
        # radix prefix cache (serving/tier/prefix_cache.py): arg wins, else
        # the strict-parsed PADDLE_TPU_PREFIX_CACHE env knob (default off)
        from ..tier.knobs import ENV_PREFIX_CACHE, parse_flag_env
        if prefix_cache is None:
            prefix_cache = parse_flag_env(ENV_PREFIX_CACHE, default=False)
        if prefix_cache is False:
            self.prefix_cache = None
        elif prefix_cache is True:
            from ..tier.prefix_cache import PrefixCache
            self.prefix_cache = PrefixCache(self.pool)
        else:
            self.prefix_cache = prefix_cache

    @staticmethod
    def _resolve_num_blocks(model, max_blocks, block_size, max_bps,
                            kv_dtype):
        """Pool-size precedence (docs/SERVING.md "Tiered KV cache"): an
        explicit ``max_blocks=`` arg wins, then an explicitly-SET
        ``PADDLE_TPU_DECODE_MAX_BLOCKS`` env (checked live, not the
        import-time default — an operator pinning the block count must
        beat any budget), then the ``PADDLE_TPU_DECODE_HBM_MB`` budget
        solve (analysis/plan.py prices model state + per-block KV bytes at
        ``kv_dtype``), else the module default."""
        if max_blocks:
            return int(max_blocks)
        import os as _os
        raw = _os.environ.get('PADDLE_TPU_DECODE_MAX_BLOCKS', '').strip()
        if raw:
            return int(raw)
        from ..tier.knobs import ENV_DECODE_HBM_MB, parse_int_env
        hbm_mb = parse_int_env(ENV_DECODE_HBM_MB, 0, minimum=1)
        if hbm_mb:
            from ...analysis.plan import solve_decode_pool_blocks
            return solve_decode_pool_blocks(
                model, hbm_mb, block_size=block_size, kv_dtype=kv_dtype,
                min_blocks=max_bps + 1)
        return DEFAULT_MAX_BLOCKS

    # -- geometry ----------------------------------------------------------
    @property
    def block_size(self):
        return self.pool.block_size

    @property
    def padded_context(self):
        """The key extent every attention read pads to — run the uncached
        reference (models/causal_lm.greedy_generate) at this pad_len for
        bitwise-identical tokens."""
        return self.pool.padded_context

    def validate(self, prompt_ids, max_new_tokens):
        """Typed admission checks; returns (prompt list, max_new int)."""
        try:
            prompt = [int(t) for t in prompt_ids]
        except (TypeError, ValueError) as e:
            raise InvalidRequest(f'prompt must be a sequence of ints: {e}')
        if not prompt:
            raise InvalidRequest('empty prompt')
        if len(prompt) > self.max_prompt_len:
            raise InvalidRequest(
                f'prompt of {len(prompt)} tokens exceeds max_prompt_len='
                f'{self.max_prompt_len}')
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise InvalidRequest(f'max_new_tokens must be >= 1, got '
                                 f'{max_new}')
        if max_new > self.max_new_tokens_cap:
            raise InvalidRequest(
                f'max_new_tokens={max_new} exceeds the engine cap '
                f'{self.max_new_tokens_cap}')
        return prompt, max_new

    def reserve_table(self, prompt_len, max_new_tokens, prompt=None):
        """Block reservation for prompt + budget (raises OutOfBlocks — the
        scheduler treats that as 'wait for a finishing slot'). With the
        prefix cache enabled and ``prompt`` given, the table's front blocks
        are shared cached-prefix blocks (``table.cached_len`` > 0) and only
        the remainder is freshly allocated."""
        total = int(prompt_len) + int(max_new_tokens)
        if self.prefix_cache is not None:
            return self.prefix_cache.acquire_table(prompt or [], total)
        return self.pool.new_table(total)

    def publish_prefix(self, prompt, table):
        """Publish ``table``'s whole-prompt blocks into the prefix cache
        (no-op when the cache is off). The scheduler calls this once the
        full prompt's K/V is cached — after a cold prefill, a suffix fill,
        or a disaggregated injection."""
        if self.prefix_cache is not None:
            self.prefix_cache.insert(prompt, table)

    def release_table(self, table):
        self.pool.free_table(table)
        _m.decode_cache_blocks_used.set(self.pool.allocator.used)

    # -- phases ------------------------------------------------------------
    def prefill(self, prompt, table, sampler=None):
        """Run the bucket-padded prompt once, writing K/V into ``table``'s
        blocks, and return the FIRST generated token — greedy, or drawn by
        ``sampler(logits_row)`` for sampled requests. Sets
        ``table.context_len = len(prompt)``."""
        from ...dygraph.tape import Tensor, no_grad_guard
        P = len(prompt)
        bucket = next(b for b in self.prompt_buckets if P <= b)
        ids = np.zeros((1, bucket), np.int64)
        ids[0, :P] = prompt
        table.context_len = P
        ctx = CacheContext(self.pool, 'prefill', [table])
        t0 = time.perf_counter()
        with self._model_lock or _NULL_LOCK:
            with no_grad_guard():
                logits = self.model(Tensor(ids, stop_gradient=True),
                                    cache=ctx)
                row = np.asarray(logits.numpy())[0, P - 1]
        dt = time.perf_counter() - t0
        _m.decode_prefill_seconds.observe(dt)
        if bucket not in self._prefill_compiled:
            self._prefill_compiled.add(bucket)
            _m.decode_prefill_compiles.inc()
        _m.decode_cache_blocks_used.set(self.pool.allocator.used)
        _m.kv_cache_bytes_in_hbm.set(self.pool.bytes_in_hbm())
        if sampler is not None:
            return int(sampler(row))
        return int(row.argmax())

    def decode_step(self, tokens, tables, return_rows=False):
        """One lockstep step over all S slots at fixed shape.

        ``tokens``: length-S list, the token to feed per slot (None =
        inactive). ``tables``: length-S list of BlockTables (None =
        inactive). For an active slot with context c, the fed token is the
        one at position c (it was sampled from the previous step/prefill
        but not yet cached); its K/V are written and attended this step.
        Returns (S,) next-token ids (greedy; garbage on inactive slots) and
        advances each active table's context_len by 1. With
        ``return_rows=True`` the raw (S, V) logits rows come back too
        (``(ids, rows)``) so the scheduler can sample non-greedy slots —
        the greedy ids are the argmax of those same rows, so requesting
        rows changes no bits."""
        from ...dygraph.tape import Tensor, no_grad_guard
        S = self.slots
        assert len(tokens) == S and len(tables) == S
        ids = np.zeros((S, 1), np.int64)
        pos = np.zeros((S, 1), np.int64)
        ctx_lens = []
        for s in range(S):
            if tables[s] is None:
                ctx_lens.append(1)          # scratch read, masked + ignored
                continue
            c = tables[s].context_len
            ids[s, 0] = tokens[s]
            pos[s, 0] = c
            tables[s].context_len = c + 1   # the fed token becomes cached
            ctx_lens.append(c + 1)
        ctx = CacheContext(self.pool, 'decode', tables, ctx_lens)
        t0 = time.perf_counter()
        with self._model_lock or _NULL_LOCK:
            with no_grad_guard():
                logits = self.model(Tensor(ids, stop_gradient=True),
                                    pos_ids=Tensor(pos, stop_gradient=True),
                                    cache=ctx)
                rows = np.asarray(logits.numpy())[:, 0]
                out = rows.argmax(-1)
        dt = time.perf_counter() - t0
        self._step_compiled = True
        _m.decode_step_seconds.observe(dt)
        _m.decode_steps.inc()
        active = sum(t is not None for t in tables)
        _m.decode_slots_active.set(active)
        _m.decode_slot_occupancy.observe(active / max(S, 1))
        # sliding-window views for /healthz slo + fleet snapshots
        _dobs.series('occupancy').observe(active / max(S, 1))
        _dobs.series('decode_step').observe(dt)
        if return_rows:
            return out, rows
        return out

    def spec_step(self, token_lists, tables):
        """One batched (S, k) speculative/multi-token step.

        ``token_lists``: length-S list; None or [] for an inactive slot,
        else UP TO ``spec_k`` tokens to feed — the slot's pending token
        first, then its draft guesses (or further prompt tokens during a
        chunked suffix fill). All fed tokens' K/V are written at positions
        context_len .. context_len+f-1 and each table's ``context_len``
        advances by f; the CALLER rolls rejected tails back by assigning
        ``table.context_len = base + accepted`` (block ids don't move —
        rollback is one integer store, and the overwritten tail positions
        are masked until rewritten, per the kv_cache scratch contract).

        Returns (S, k, V) logits rows: row j of a slot is the target
        model's distribution AFTER fed tokens 0..j — bitwise-identical to
        the (S, 1) lockstep row at the same context (the multi-query
        `paged_attention` staircase; tests/framework/test_spec_decode.py
        asserts it across ragged accept lengths). Padded lanes (j >= f)
        are garbage on scratch reads and must be ignored."""
        from ...dygraph.tape import Tensor, no_grad_guard
        S, K = self.slots, self.spec_k
        assert len(token_lists) == S and len(tables) == S
        ids = np.zeros((S, K), np.int64)
        pos = np.zeros((S, K), np.int64)
        ctx_lens, fed_counts = [], []
        for s in range(S):
            toks = token_lists[s]
            if tables[s] is None or not toks:
                ctx_lens.append(1)      # scratch read, masked + ignored
                fed_counts.append(0)
                continue
            f = min(len(toks), K)
            c = tables[s].context_len
            ids[s, :f] = toks[:f]
            pos[s, :f] = np.arange(c, c + f)
            pos[s, f:] = c + max(f - 1, 0)   # padded lanes: in-range dummy
            tables[s].context_len = c + f
            ctx_lens.append(c + 1)
            fed_counts.append(f)
        ctx = CacheContext(self.pool, 'decode', tables, ctx_lens,
                           fed_counts=fed_counts, window=K)
        t0 = time.perf_counter()
        with self._model_lock or _NULL_LOCK:
            with no_grad_guard():
                logits = self.model(Tensor(ids, stop_gradient=True),
                                    pos_ids=Tensor(pos, stop_gradient=True),
                                    cache=ctx)
                rows = np.asarray(logits.numpy())
        dt = time.perf_counter() - t0
        self._spec_compiled = True
        _m.decode_step_seconds.observe(dt)      # it IS the decode step
        _m.decode_spec_verify_seconds.observe(dt)
        _m.decode_steps.inc()
        _m.decode_spec_rounds.inc()
        active = sum(t is not None for t in tables)
        _m.decode_slots_active.set(active)
        _m.decode_slot_occupancy.observe(active / max(S, 1))
        _dobs.series('occupancy').observe(active / max(S, 1))
        _dobs.series('decode_step').observe(dt)
        return rows

    def inject_prefill(self, table, payload):
        """Receive a disaggregated prefill (serving/tier/disagg.py): write
        the payload's whole K/V blocks into ``table``'s first blocks of
        THIS pool and mark the prompt cached. Returns the payload's first
        greedy token. ``table.cached_len`` blocks at the front (shared
        prefix-cache blocks) are already filled and are skipped."""
        bs = self.pool.block_size
        if payload.block_size != bs:
            raise InvalidRequest(
                f'handoff block_size {payload.block_size} != engine '
                f'block_size {bs}')
        skip = table.cached_len // bs          # shared blocks already filled
        nb = payload.num_blocks
        if nb > len(table.blocks):
            raise InvalidRequest(
                f'handoff carries {nb} blocks but the table reserves only '
                f'{len(table.blocks)}')
        for layer, (k, v) in enumerate(payload.layers):
            ks = vs = None
            if payload.scales is not None:
                ks, vs = payload.scales[layer]
            if skip:
                k, v = k[:, skip:], v[:, skip:]
                if ks is not None:
                    ks, vs = ks[:, skip:], vs[:, skip:]
            if k.shape[1]:
                self.pool.write_whole_blocks(
                    layer, table.blocks[skip:nb], k, v,
                    k_scale=ks, v_scale=vs)
        table.context_len = payload.context_len
        _m.decode_cache_blocks_used.set(self.pool.allocator.used)
        _m.kv_cache_bytes_in_hbm.set(self.pool.bytes_in_hbm())
        return int(payload.first_token)

    # -- warmup ------------------------------------------------------------
    @property
    def warmed(self):
        """True once the whole prefill bucket ladder AND the lockstep
        decode-step shape have compiled (via :meth:`warmup` or organic
        traffic). Surfaced through ``/healthz`` so the serving-tier router
        never sends traffic into a cold replica's compile cliff."""
        return (self._step_compiled
                and (self._spec_compiled or not self.spec_enabled)
                and all(b in self._prefill_compiled
                        for b in self.prompt_buckets))

    def warmup(self):
        """Precompile the prefill ladder + the decode-step shape (+ the
        (S, k) speculative verify shape when enabled) before traffic
        arrives (same contract as InferenceEngine.warmup). Returns
        {phase: seconds}. Uses temporary blocks; the pool ends unchanged."""
        timings = {}
        for bucket in self.prompt_buckets:
            # reserve spec_k headroom so the warmup spec_step below can
            # write its window without outgrowing the throwaway table
            table = self.reserve_table(bucket, self.spec_k
                                       if self.spec_enabled else 1)
            t0 = time.perf_counter()
            tok = self.prefill([1] * bucket, table)
            timings[f'prefill_{bucket}'] = time.perf_counter() - t0
            # one decode step over slot 0 also warms the step shape
            tokens = [tok] + [None] * (self.slots - 1)
            tables = [table] + [None] * (self.slots - 1)
            t0 = time.perf_counter()
            self.decode_step(tokens, tables)
            timings.setdefault('decode_step',
                               time.perf_counter() - t0)
            if self.spec_enabled and not self._spec_compiled:
                base = table.context_len
                feed = [[tok] * (self.spec_k - 1)] \
                    + [None] * (self.slots - 1)
                t0 = time.perf_counter()
                self.spec_step(feed, tables)
                timings['spec_step'] = time.perf_counter() - t0
                table.context_len = base      # roll the warmup feed back
            self.release_table(table)
        return timings
