"""Slot-based continuous-batching scheduler + per-request token streams.

The scheduling model is S fixed decode slots stepped in lockstep:

    submit() ─ validate ─▶ bounded waiting queue ─▶ worker loop, per step:
                  │               │                   1. expire deadlines
           InvalidRequest     Overloaded              2. admit waiting →
           (never queued)    (queue full)                free slots (prefill)
                                                      3. ONE decode step,
                                                         all S slots
                                                      4. emit tokens, free
                                                         finished slots
                                                      ▼
                                          per-request GenerationStream

**Continuous vs drain** (``admission=``): 'continuous' admits into freed
slots every step — the batch never drains, so slot occupancy stays near 1
under backlog. 'drain' (the strawman tools/bench_decode.py measures
against) only admits when ALL slots are free: short requests finish early
and their slots idle until the longest in the wave completes. The measured
gap on a mixed-length workload is the PR's ≥1.5× acceptance bar
(PERF.md §13).

Admission takes the request's full block reservation (prompt + token
budget) up front, so a generation can never die of OutOfBlocks mid-flight;
when the pool can't cover the next waiting request the scheduler simply
keeps stepping until a finishing slot frees blocks (FIFO admission — no
starvation of big requests behind small ones).

Deadlines bound WAITING only: once a request holds a slot it runs to
completion (aborting mid-generation would waste the prefill — the
ROADMAP's preemption item is about checkpointed resume, not dropping
work). Backpressure and drain/fail-fast close mirror MicroBatcher.
"""
from __future__ import annotations

import collections
import contextlib
import os
import queue
import threading
import time
import uuid

from .. import metrics as _m
from ...observability import distributed as _dobs
from ..breaker import CircuitBreaker
from ..errors import (DeadlineExceeded, EngineClosed, EngineUnhealthy,
                      InvalidRequest, Overloaded, OutOfBlocks, ServingError)
from ..batcher import DEFAULT_QUEUE_DEPTH
from .sampling import SamplingParams, TokenSampler

__all__ = ['DecodeScheduler', 'GenerationStream']

_END = object()


class GenerationStream:
    """Per-request handle: iterate tokens as they decode, or block for the
    full result.

        for tok in stream:            # per-token streaming
            ...
        toks = stream.result(30)      # or: block until done

    ``finish_reason``: 'stop' (eos) | 'length' (budget) | None while
    running. Failures (engine error, deadline, shutdown) raise from both
    the iterator and ``result()``.

    Identity (``meta`` / the final HTTP NDJSON line): ``replica_id`` names
    the serving process, ``request_id`` is restart-safe — a fresh random
    component per submission (or the client's pinned id), so retries after
    a replica restart or a router failover never collide and clients can
    correlate the attempts of one logical request across replicas. For
    SAMPLED requests the request_id is also the stream seed (sampling.py):
    replaying the same id + params reproduces the token stream bitwise."""

    def __init__(self, prompt_len, max_new_tokens, replica_id=None,
                 request_id=None, trace_id=None):
        self.prompt_len = prompt_len
        self.max_new_tokens = max_new_tokens
        self.replica_id = replica_id
        self.request_id = request_id or uuid.uuid4().hex[:16]
        self.trace_id = trace_id
        self._q = queue.Queue()
        self._tokens = []
        self._done = threading.Event()
        self._exc = None
        self.finish_reason = None

    @property
    def meta(self):
        """Result metadata: {'request_id', 'replica_id'} (+ 'trace_id' for
        sampled-trace requests) — stable from submission, valid
        before/after completion."""
        meta = {'request_id': self.request_id, 'replica_id': self.replica_id}
        if self.trace_id is not None:
            meta['trace_id'] = self.trace_id
        return meta

    # -- consumer side -----------------------------------------------------
    def __iter__(self):
        return self.iter_tokens()

    def iter_tokens(self, timeout=None):
        """Yield token ids as they decode. ``timeout`` bounds the wait for
        EACH token (TimeoutError) — the HTTP handler uses it so a stuck
        stream cannot pin a connection thread forever."""
        while True:
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f'no token within {timeout}s (generated '
                    f'{len(self._tokens)} so far)')
            if item is _END:
                if self._exc is not None:
                    raise self._exc
                return
            yield item

    def result(self, timeout=None):
        """All generated token ids; raises the request's failure."""
        if not self._done.wait(timeout):
            raise TimeoutError('generation not completed in time')
        if self._exc is not None:
            raise self._exc
        return list(self._tokens)

    def done(self):
        return self._done.is_set()

    @property
    def tokens(self):
        """Snapshot of tokens emitted so far."""
        return list(self._tokens)

    # -- scheduler side ----------------------------------------------------
    def _emit(self, token):
        self._tokens.append(int(token))
        self._q.put(int(token))

    def _finish(self, reason):
        self.finish_reason = reason
        self._done.set()
        self._q.put(_END)

    def _fail(self, exc):
        self._exc = exc
        self._done.set()
        self._q.put(_END)


class _Request:
    __slots__ = ('prompt', 'max_new_tokens', 'eos_id', 'stream', 'deadline',
                 'enqueued_at', 'table', 'next_token', 'generated',
                 'pending_prompt', 'prefilling', 'handoff_pending',
                 'sampling', 'sampler', 'history', 'trace', 'enqueued_perf',
                 'handoff_t0')

    def __init__(self, prompt, max_new_tokens, eos_id, deadline,
                 replica_id=None, sampling=None, request_id=None,
                 trace=None):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_id = eos_id
        # distributed trace carrier (observability.TraceContext | None):
        # spans recorded here parent under the router's dispatch span
        self.trace = trace if (trace is not None and trace.sampled) else None
        self.stream = GenerationStream(
            len(prompt), max_new_tokens, replica_id=replica_id,
            request_id=request_id,
            trace_id=self.trace.trace_id if self.trace else None)
        self.deadline = deadline
        self.enqueued_at = time.monotonic()
        self.enqueued_perf = time.perf_counter()
        self.handoff_t0 = None
        self.table = None
        self.next_token = None        # sampled but not yet cached/emitted?
        self.generated = 0
        # per-request sampling: sampler is None on the greedy path (exact
        # argmax, bitwise-unchanged); sampled draws are keyed off the
        # stream's restart-safe request_id → replayable (sampling.py)
        self.sampling = sampling or SamplingParams()
        self.sampler = (None if self.sampling.greedy else
                        TokenSampler(self.sampling, self.stream.request_id))
        # prompt + emitted tokens — what the speculative drafter continues
        # from (its last element is the pending uncached token)
        self.history = list(prompt)
        # chunked suffix fill (prefix-cache hit): prompt tokens still to be
        # fed through the lockstep step; while prefilling, step outputs are
        # discarded (the next fed token is forced to the prompt)
        self.pending_prompt = None
        self.prefilling = False
        # disaggregation: admitted, slot reserved, waiting for the prefill
        # replica's KV payload — inactive in the lockstep step until then
        self.handoff_pending = False

    def expired(self, now):
        return self.deadline is not None and now > self.deadline


class DecodeScheduler:
    """Continuous-batching front end over a :class:`DecodeEngine`.

    - ``queue_depth``: waiting-queue bound → typed ``Overloaded``.
    - ``admission``: 'continuous' (default) | 'drain' (bench strawman).
    - ``default_timeout_ms``: waiting deadline applied when submit() gets
      none (None = wait forever).
    """

    def __init__(self, engine, queue_depth=DEFAULT_QUEUE_DEPTH,
                 admission='continuous', default_timeout_ms=None,
                 breaker_failures=None, breaker_reset_s=None, start=True,
                 replica_id=None, disagg=None, drafter=None):
        if admission not in ('continuous', 'drain'):
            raise ValueError(f"admission must be 'continuous' or 'drain', "
                             f"got {admission!r}")
        self.engine = engine
        # speculative decoding (engine.spec_enabled): the engine owns the
        # batched (S, k) verify step; the scheduler owns the DRAFTER —
        # proposals are host-side policy. ``drafter`` may be a name
        # ('ngram' / 'draft_model' / 'off'), a duck-typed .propose object,
        # or None → the PADDLE_TPU_SPEC_DRAFTER knob (default 'ngram').
        self.drafter = None
        self._spec_drafted = 0
        self._spec_accepted = 0
        if getattr(engine, 'spec_enabled', False):
            from ..tier.knobs import ENV_SPEC_DRAFTER, parse_choice_env
            from .drafter import DRAFTER_CHOICES, build_drafter
            if drafter is None:
                drafter = parse_choice_env(ENV_SPEC_DRAFTER,
                                           DRAFTER_CHOICES, 'ngram')
            self.drafter = build_drafter(
                drafter, getattr(engine, 'padded_context', 0))
        # identity stamped into every GenerationStream's result metadata
        # (serving-tier failover correlation); free-form, not a strict knob
        self.replica_id = (replica_id
                           or os.environ.get('PADDLE_TPU_REPLICA_ID')
                           or f'replica-{os.getpid()}')
        # disaggregated prefill (serving/tier/disagg.py): cache-miss
        # prompts hand off to prefill-role replicas instead of stalling
        # the lockstep decode loop on an inline bucket forward
        self.disagg = disagg
        # circuit breaker (serving/breaker.py): consecutive engine failures
        # (prefill or lockstep step) trip it — waiting requests fail fast
        # with EngineUnhealthy, /healthz reports degraded, a half-open probe
        # re-admits traffic once the engine answers
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_failures, reset_after_s=breaker_reset_s,
            metrics=_m.DECODE_BREAKER_METRICS, name='decode engine')
        self.queue_depth = int(queue_depth)
        self.admission = admission
        self.default_timeout_ms = default_timeout_ms
        self._waiting = collections.deque()
        self._slots = [None] * engine.slots      # _Request | None
        self._cv = threading.Condition()
        self._closing = False
        self._abort = False
        self._closed = False
        self._worker = threading.Thread(target=self._worker_loop,
                                        name='paddle-tpu-decode-scheduler',
                                        daemon=True)
        if start:
            self._worker.start()

    # -- client side -------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=16, eos_id=None,
               timeout_ms=None, sampling=None, request_id=None, trace=None):
        """Validate and enqueue one generation; returns its
        :class:`GenerationStream`. Raises InvalidRequest / Overloaded /
        EngineUnhealthy (breaker open) / EngineClosed (all pre-enqueue).

        ``sampling``: None (greedy) | dict | SamplingParams — typed
        validation happens HERE, pre-enqueue, naming the bad field.
        ``request_id``: optional client-pinned id; for sampled requests it
        seeds the stream, so resubmitting the same id + params replays the
        exact token sequence (after a restart, on another replica, ...).
        ``trace``: optional :class:`observability.TraceContext` carried in
        from the HTTP front end — queue-wait/prefill/per-token spans of
        this generation are recorded under it (docs/OBSERVABILITY.md)."""
        if not self.breaker.allow():
            raise EngineUnhealthy('decode engine',
                                  self.breaker.consecutive_failures)
        try:
            prompt, max_new = self.engine.validate(prompt_ids,
                                                   max_new_tokens)
            params = SamplingParams.validate(sampling)
            if request_id is not None:
                request_id = str(request_id)
                if not 0 < len(request_id) <= 128 or any(
                        c in request_id for c in '\r\n'):
                    raise InvalidRequest(
                        'request_id must be 1-128 characters with no '
                        'newlines')
        except Exception:
            _m.decode_requests_rejected_invalid.inc()
            raise
        if timeout_ms is None:
            timeout_ms = self.default_timeout_ms
        deadline = None if timeout_ms is None \
            else time.monotonic() + float(timeout_ms) / 1e3
        req = _Request(prompt, max_new,
                       self.engine.eos_id if eos_id is None else eos_id,
                       deadline, replica_id=self.replica_id,
                       sampling=params, request_id=request_id, trace=trace)
        if req.trace is not None:
            _m.trace_requests_sampled.inc()
        with self._cv:
            if self._closing:
                raise EngineClosed('decode scheduler is shutting down')
            if len(self._waiting) >= self.queue_depth:
                _m.decode_requests_rejected_overload.inc()
                raise Overloaded(len(self._waiting))
            self._waiting.append(req)
            _m.decode_requests_accepted.inc()
            _m.decode_queue_depth.set(len(self._waiting))
            _dobs.series('queue_depth').observe(len(self._waiting))
            self._cv.notify()
        return req.stream

    def generate(self, prompt_ids, max_new_tokens=16, eos_id=None,
                 timeout_ms=None, result_timeout=120.0):
        """Synchronous convenience: submit + wait for the full token list."""
        return self.submit(prompt_ids, max_new_tokens, eos_id,
                           timeout_ms).result(result_timeout)

    def pending(self):
        with self._cv:
            return len(self._waiting)

    def active(self):
        with self._cv:
            return sum(r is not None for r in self._slots)

    # -- worker side -------------------------------------------------------
    def _expire_waiting(self, now):
        kept = collections.deque()
        for req in self._waiting:
            if req.expired(now):
                _m.decode_requests_deadline_missed.inc()
                req.stream._fail(DeadlineExceeded(
                    f'deadline expired after {now - req.enqueued_at:.3f}s '
                    f'waiting for a decode slot'))
            else:
                kept.append(req)
        self._waiting = kept
        _m.decode_queue_depth.set(len(self._waiting))

    def _admit_locked(self):
        """Move waiting requests into free slots (FIFO; stops at the first
        one the pool cannot cover). Returns the admitted requests — their
        prefill runs OUTSIDE the lock."""
        if self.admission == 'drain' and any(
                r is not None for r in self._slots):
            return []
        admitted = []
        for i, slot in enumerate(self._slots):
            if slot is not None or not self._waiting:
                continue
            req = self._waiting[0]
            try:
                req.table = self.engine.reserve_table(len(req.prompt),
                                                      req.max_new_tokens,
                                                      prompt=req.prompt)
            except OutOfBlocks:
                break                 # FIFO: wait for blocks, don't skip
            self._waiting.popleft()
            self._slots[i] = req
            admitted.append(req)
        _m.decode_queue_depth.set(len(self._waiting))
        return admitted

    def _publish(self, req):
        """Publish the fully-cached prompt into the engine's prefix cache
        (no-op for cache-off and duck-typed engines)."""
        if getattr(self.engine, 'prefix_cache', None) is not None:
            self.engine.publish_prefix(req.prompt, req.table)

    def _trace_span(self, req, name, start_perf, end_perf, **args):
        """Record one replica-side span of a traced request (no-op when the
        request carries no sampled trace — one None check)."""
        if req.trace is None:
            return
        _m.trace_spans_recorded.inc()
        _dobs.record_span(req.trace.child(), name, start_perf, end_perf,
                          request_id=req.stream.request_id,
                          replica_id=self.replica_id, **args)

    def _prefill(self, req):
        self._trace_span(req, 'replica/queue_wait', req.enqueued_perf,
                        time.perf_counter())
        cached = getattr(req.table, 'cached_len', 0)
        if cached:
            # prefix-cache hit: the front of the table is already-filled
            # shared blocks; the uncached suffix rides the SAME lockstep
            # decode step as everyone else's generation (chunked prefill —
            # bitwise-identical rows by the PR 6 parity contract), so a
            # long shared prompt costs only its suffix
            req.table.context_len = cached
            req.next_token = req.prompt[cached]
            req.pending_prompt = collections.deque(req.prompt[cached + 1:])
            req.prefilling = True
            return
        if self.disagg is not None and req.sampler is None:
            # cache miss under disaggregation: ship the prompt to a
            # prefill-role replica; this slot stays inactive (and the
            # decode loop keeps stepping) until the KV payload lands.
            # Sampled requests prefill INLINE — the handoff payload carries
            # a greedy first token, not logits, so the draw must happen
            # here where the row is
            req.handoff_pending = True
            req.handoff_t0 = time.perf_counter()
            self.disagg.submit(req, req.prompt, req.max_new_tokens)
            return
        t0 = time.perf_counter()
        try:
            if req.sampler is None:     # kwarg-free call: duck-typed
                first = self.engine.prefill(req.prompt, req.table)
            else:
                first = self.engine.prefill(
                    req.prompt, req.table,
                    sampler=lambda row: self._pick_token(req, row))
        except Exception as e:
            self._fail_request(req, e)
            self._record_engine_failure()
            return
        self._trace_span(req, 'replica/prefill', t0, time.perf_counter(),
                         prompt_len=len(req.prompt))
        self.breaker.record_success()
        self._publish(req)
        self._emit_token(req, first)

    def _drain_handoffs(self, timeout=0.0):
        """Apply finished prefill handoffs: inject the KV payload into the
        decode pool (worker thread — the engine has ONE owner) and emit the
        first token. Payloads for requests already failed/closed are
        dropped (their table is gone)."""
        if self.disagg is None:
            return
        for req, payload, exc in self.disagg.drain_completed(timeout):
            if req not in self._slots or req.table is None:
                continue              # failed or closed while in flight
            req.handoff_pending = False
            if exc is not None:
                self._fail_request(req, exc)
                self._record_engine_failure()
                continue
            try:
                first = self.engine.inject_prefill(req.table, payload)
            except Exception as e:
                self._fail_request(req, e)
                self._record_engine_failure()
                continue
            if req.handoff_t0 is not None:
                self._trace_span(req, 'replica/handoff_wait',
                                 req.handoff_t0, time.perf_counter(),
                                 prompt_len=len(req.prompt))
            self.breaker.record_success()
            self._publish(req)
            self._emit_token(req, first)

    def _record_engine_failure(self):
        """Book one engine-failure batch with the breaker; on a trip, fail
        everything still waiting — it would only burn its deadline against
        a broken engine (in-flight slots were already failed by
        isolation)."""
        if not self.breaker.record_failure():
            return
        exc = EngineUnhealthy('decode engine',
                              self.breaker.consecutive_failures)
        with self._cv:
            failed = len(self._waiting)
            while self._waiting:
                self._waiting.popleft().stream._fail(exc)
            _m.decode_queue_depth.set(0)
        if failed:
            _m.decode_requests_failed.inc(failed)

    def _pick_token(self, req, row):
        """Next token from a logits row: the request's deterministic
        sampler (indexed by tokens generated so far — the replay contract)
        or exact greedy argmax."""
        if req.sampler is not None:
            tok = req.sampler.sample(row, req.generated)
            _m.decode_tokens_sampled.inc()
            return int(tok)
        return int(row.argmax())

    def _emit_token(self, req, token):
        """Account one sampled token; marks the request finished when it
        hits eos or its budget. The token still needs to be FED to the next
        decode step (its K/V are uncached) unless the request finished."""
        req.generated += 1
        req.history.append(int(token))
        req.stream._emit(token)
        _m.decode_tokens_generated.inc()
        _dobs.series('tokens').observe(1.0)
        if req.generated == 1:
            ttft = time.perf_counter() - req.enqueued_perf
            _m.decode_ttft_seconds.observe(ttft)
            _dobs.series('ttft').observe(ttft)
        if req.eos_id is not None and int(token) == int(req.eos_id):
            self._retire(req, 'stop')
        elif req.generated >= req.max_new_tokens:
            self._retire(req, 'length')
        else:
            req.next_token = int(token)

    def _retire(self, req, reason):
        self.engine.release_table(req.table)
        req.table = None
        self._slots[self._slots.index(req)] = None
        req.stream._finish(reason)
        _m.decode_requests_completed.inc()

    def _fail_request(self, req, exc):
        if req.table is not None:
            self.engine.release_table(req.table)
            req.table = None
        if req in self._slots:
            self._slots[self._slots.index(req)] = None
        _m.decode_requests_failed.inc()
        req.stream._fail(exc if isinstance(exc, ServingError)
                         else ServingError(
                             f'generation failed: '
                             f'{type(exc).__name__}: {exc}'))

    def _step(self):
        """One lockstep decode step over the current slots. Handoff-pending
        slots are inactive lanes (scratch reads); suffix-filling slots feed
        their next PROMPT token and their sampled output is discarded until
        the prompt is exhausted — the step after the last prompt token
        yields the first generated token."""
        live = [r for r in self._slots if r is not None]
        active = [r for r in live if not r.handoff_pending]
        if not active:
            return bool(live)         # only pending handoffs: work remains
        tokens = [r.next_token if r is not None and not r.handoff_pending
                  else None for r in self._slots]
        tables = [r.table if r is not None and not r.handoff_pending
                  else None for r in self._slots]
        # greedy-only batches take the original call (byte-identical path);
        # a sampled slot that will EMIT this step needs its logits row
        rows = None
        need_rows = any(r.sampler is not None and not r.prefilling
                        for r in active)
        traced = [r for r in active if r.trace is not None]
        t0 = time.perf_counter() if traced else 0.0
        try:
            if need_rows:
                out, rows = self.engine.decode_step(tokens, tables,
                                                    return_rows=True)
            else:
                out = self.engine.decode_step(tokens, tables)
        except Exception as e:
            for req in active:      # isolate: fail the batch, keep serving
                self._fail_request(req, e)
            self._record_engine_failure()
            return True
        t1 = time.perf_counter() if traced else 0.0
        self.breaker.record_success()
        for i, req in enumerate(self._slots):
            if req is None or req.handoff_pending:
                continue
            if req.prefilling:
                if req.pending_prompt:
                    req.next_token = req.pending_prompt.popleft()
                    continue          # still feeding the prompt suffix
                # the step above consumed the LAST prompt token: its whole
                # K/V is now cached — publish, then emit the first token
                req.prefilling = False
                self._publish(req)
            if req.sampler is not None and rows is not None:
                self._emit_token(req, self._pick_token(req, rows[i]))
            else:
                self._emit_token(req, int(out[i]))
            if req.trace is not None:
                self._trace_span(req, 'replica/token', t0, t1,
                                 index=req.generated - 1)
        return True

    def _spec_step(self):
        """One speculative (S, k) verify round (engine.spec_enabled).

        Greedy slots feed their pending token plus up to k-1 drafter
        guesses; the target model's (S, k, V) rows verify them all in ONE
        step and the longest prefix the target agrees with is emitted
        (rows are bitwise-identical to the lockstep rows, so the emitted
        stream equals non-speculative greedy exactly). Rejected tails roll
        the block table back — one integer store; the stale K/V positions
        are masked until overwritten (kv_cache scratch contract). Sampled
        slots ride the same batched step with a single fed token (their
        draw stays exact + replayable); suffix-filling slots feed up to k
        prompt tokens per round (chunked prefill, k× fewer steps)."""
        live = [r for r in self._slots if r is not None]
        active = [r for r in live if not r.handoff_pending]
        if not active:
            return bool(live)
        K = self.engine.spec_k
        fed = [None] * len(self._slots)
        tables = [None] * len(self._slots)
        bases = [0] * len(self._slots)
        for i, req in enumerate(self._slots):
            if req is None or req.handoff_pending:
                continue
            tables[i] = req.table
            bases[i] = req.table.context_len
            if req.prefilling:
                toks = [req.next_token]
                while len(toks) < K and req.pending_prompt:
                    toks.append(req.pending_prompt.popleft())
            elif req.sampler is not None:
                toks = [req.next_token]
            else:
                # never draft past the budget: the last verify round feeds
                # exactly the remaining token allowance
                budget = req.max_new_tokens - req.generated
                n = min(K, max(budget, 1)) - 1
                drafts = []
                if n > 0 and self.drafter is not None:
                    # the draft model shares the process-global no_grad
                    # flag with the engine models — serialize under the
                    # same lock disaggregation uses (None → no-op)
                    with (getattr(self.engine, '_model_lock', None)
                          or contextlib.nullcontext()):
                        drafts = [int(t) for t in self.drafter.propose(
                            req.history, n)][:n]
                toks = [req.next_token] + drafts
            fed[i] = toks
        traced = [r for r in active if r.trace is not None]
        t0 = time.perf_counter() if traced else 0.0
        try:
            rows = self.engine.spec_step(fed, tables)
        except Exception as e:
            for req in active:      # isolate: fail the batch, keep serving
                self._fail_request(req, e)
            self._record_engine_failure()
            return True
        t1 = time.perf_counter() if traced else 0.0
        self.breaker.record_success()
        for i, req in enumerate(self._slots):
            if req is None or req.handoff_pending:
                continue
            toks = fed[i]
            f = len(toks)
            if req.prefilling:
                if req.pending_prompt:
                    req.next_token = req.pending_prompt.popleft()
                    continue          # all fed prompt tokens stay cached
                req.prefilling = False
                self._publish(req)
                self._emit_token(req, self._pick_token(req, rows[i, f - 1]))
                continue
            drafted = f - 1
            emitted = 0
            j = 0
            while j < f:
                tok = self._pick_token(req, rows[i, j])
                self._emit_token(req, tok)
                emitted += 1
                if req.table is None:
                    break             # retired (eos / budget) mid-round
                if j + 1 < f and int(toks[j + 1]) == tok:
                    j += 1            # draft confirmed; keep verifying
                    continue
                break                 # first rejection (or window end)
            if req.table is not None:
                # commit the accepted prefix, roll back the rejected tail
                req.table.context_len = bases[i] + emitted
            self._trace_span(req, 'replica/verify_round', t0, t1,
                             fed=f, emitted=emitted)
            _m.decode_spec_accept_len.observe(emitted)
            if drafted:
                self._spec_drafted += drafted
                self._spec_accepted += emitted - 1
                _m.decode_spec_draft_tokens.inc(drafted)
                if emitted > 1:
                    _m.decode_spec_accepted_tokens.inc(emitted - 1)
                _m.decode_spec_acceptance.set(
                    self._spec_accepted / max(self._spec_drafted, 1))
        return True

    def _fail_all_locked(self):
        """Fail-fast shutdown: error every waiting and in-flight request.
        Runs on the WORKER thread (slot state is worker-owned; the close()
        caller only raises the abort flag), so no step can race a release."""
        while self._waiting:
            self._waiting.popleft().stream._fail(EngineClosed(
                'decode scheduler shut down before this request ran'))
        _m.decode_queue_depth.set(0)
        for i, req in enumerate(self._slots):
            if req is not None:
                self.engine.release_table(req.table)
                req.table = None
                self._slots[i] = None
                req.stream._fail(EngineClosed(
                    'decode scheduler shut down mid-generation'))
        _m.decode_slots_active.set(0)

    def _worker_loop(self):
        while True:
            with self._cv:
                if self._closing and self._abort:
                    self._fail_all_locked()
                    break
                self._expire_waiting(time.monotonic())
                admitted = self._admit_locked()
            for req in admitted:
                self._prefill(req)
            # finished prefill handoffs join before the step; when ONLY
            # handoffs are in flight, block briefly on the completion
            # queue instead of spinning the loop hot
            only_pending = (self.disagg is not None
                            and any(r is not None and r.handoff_pending
                                    for r in self._slots)
                            and all(r is None or r.handoff_pending
                                    for r in self._slots))
            self._drain_handoffs(0.01 if only_pending else 0.0)
            if getattr(self.engine, 'spec_enabled', False):
                stepped = self._spec_step()
            else:
                stepped = self._step()
            if not stepped and not admitted:
                with self._cv:
                    if self._closing:
                        if self._abort:
                            self._fail_all_locked()
                        if not self._waiting:
                            break
                    else:
                        self._cv.wait(timeout=0.05)

    # -- lifecycle ---------------------------------------------------------
    def close(self, drain=True, timeout=None):
        """Stop admission; ``drain=True`` runs every admitted AND waiting
        generation to completion, ``drain=False`` fails waiting requests
        and in-flight generations fast with EngineClosed (the failing
        itself happens on the worker thread — slot state has one owner)."""
        with self._cv:
            first = not self._closing
            self._closing = True
            if first:
                self._abort = not drain
            elif not drain:
                # escalation: a drain already in progress is converted to
                # fail-fast (server.py's SIGTERM drain-timeout cap)
                self._abort = True
            self._cv.notify_all()
        if self._worker.is_alive():
            self._worker.join(timeout)
        self._closed = True

    @property
    def closed(self):
        return self._closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
