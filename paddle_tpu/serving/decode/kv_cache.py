"""Paged KV-cache pool: fixed-size blocks, free-list allocator, per-request
block tables (docs/SERVING.md "Stateful decode"; layout per the TPU paged-
attention kernel: (num_kv_heads, num_blocks, block_size, head_dim)).

Why paged: a contiguous per-request KV buffer must be sized for the WORST
CASE length at admission, so short requests strand memory and long ones
fragment it. Blocks fix both — a request holds exactly
``ceil(context / block_size)`` blocks (plus its reservation), the free list
recycles them the moment a slot finishes, and the attention ops read
through the block table so the cache never moves.

Sizing happens ONCE at engine start (`PADDLE_TPU_DECODE_{SLOTS,BLOCK_SIZE,
MAX_BLOCKS}`); per-layer arrays allocate lazily on the first prefill (head
count / head dim are discovered from the model's first K projection, so the
pool needs no model config duplicated into it).

Block 0 is the **scratch block**: never allocated, the padding target for
inactive decode slots and short block tables. Writes to it are harmless
(masked by context lengths — and masked probabilities are *exactly* zero in
the XLA fallback, so stale block contents can never bleed between requests;
tests/ops/test_paged_attention.py proves reuse-after-free is clean).

Functional updates: jax arrays are immutable, so writes go through jitted
scatters with the pool array DONATED — XLA updates in place instead of
copying the pool per token (the same donation lever as PR 1's executor).

Quantized storage (``kv_dtype``, docs/SERVING.md "Tiered KV cache"): the
pools hold payload at ``f32`` (exact, the default — this path is
bitwise-unchanged), ``bf16`` (half the bytes; decode reads cast back to
f32 — an exact roundtrip for every representable value), or ``int8``
(quarter the bytes: one symmetric int8 row + one f32 scale per
(head, position) row via quant_collectives.rowwise_quantize — the PR 9/15
sparse-push codec; KV rows and embedding rows are the same shape problem).
Quantization happens AT THE WRITE (prefill block scatter, decode token
scatter, speculative window, whole-block handoff injection) and
dequantization AT THE READ inside `paged_attention` /
`paged_prefill_attention`, after the per-slot gather — so the resident
pool never exists at f32. The scratch-block masking contract survives
every dtype: scales init to 0.0, so an unwritten int8 row dequantizes to
exact zeros, and masked probabilities are exactly zero regardless.
"""
from __future__ import annotations

import functools
import os
import threading

import jax
import numpy as np

from ..errors import InvalidRequest, OutOfBlocks

__all__ = ['BlockAllocator', 'BlockTable', 'KVCachePool', 'CacheContext',
           'DEFAULT_SLOTS', 'DEFAULT_BLOCK_SIZE', 'DEFAULT_MAX_BLOCKS',
           'SCRATCH_BLOCK', 'KV_PAYLOAD_DTYPES', 'KV_DTYPE_CODES',
           'kv_row_bytes']

DEFAULT_SLOTS = int(os.environ.get('PADDLE_TPU_DECODE_SLOTS', '8'))
DEFAULT_BLOCK_SIZE = int(os.environ.get('PADDLE_TPU_DECODE_BLOCK_SIZE', '16'))
DEFAULT_MAX_BLOCKS = int(os.environ.get('PADDLE_TPU_DECODE_MAX_BLOCKS',
                                        '256'))

SCRATCH_BLOCK = 0

# storage payload width per element, by kv_dtype; int8 additionally carries
# one f32 scale per (head, position) row — kv_row_bytes() is the closed
# form the pool-sizing solve and the analysis bytes model both price
KV_PAYLOAD_DTYPES = {'f32': 'float32', 'bf16': 'bfloat16', 'int8': 'int8'}
_KV_PAYLOAD_BYTES = {'f32': 4, 'bf16': 2, 'int8': 1}
# stable small-int codes: the kv_cache_dtype gauge and the disagg KVPayload
# wire meta both speak these (0 is also what a legacy 3-int meta implies)
KV_DTYPE_CODES = {'f32': 0, 'bf16': 1, 'int8': 2}


def kv_row_bytes(head_dim, kv_dtype):
    """Bytes of ONE cached K or V row (one head × one token position) at
    ``kv_dtype``: payload + (int8 only) its f32 row scale."""
    if kv_dtype not in _KV_PAYLOAD_BYTES:
        raise ValueError(
            f'kv_dtype={kv_dtype!r} is not supported; supported values: '
            + ', '.join(repr(c) for c in KV_PAYLOAD_DTYPES))
    return (int(head_dim) * _KV_PAYLOAD_BYTES[kv_dtype]
            + (4 if kv_dtype == 'int8' else 0))


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_blocks(pages, block_ids, vals):
    """pages (H, NB, BS, D) ← vals (H, nb, BS, D) at block_ids (nb,)."""
    return pages.at[:, block_ids].set(vals)


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_tokens(pages, block_ids, offsets, vals):
    """pages (H, NB, BS, D) ← vals (H, S, D) at (block_ids, offsets) (S,)."""
    return pages.at[:, block_ids, offsets].set(vals)


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_block_scales(scales, block_ids, vals):
    """scales (H, NB, BS) ← vals (H, nb, BS) at block_ids (nb,)."""
    return scales.at[:, block_ids].set(vals)


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_token_scales(scales, block_ids, offsets, vals):
    """scales (H, NB, BS) ← vals (H, S) at (block_ids, offsets) (S,)."""
    return scales.at[:, block_ids, offsets].set(vals)


class BlockAllocator:
    """Refcounted free-list block allocator. Block 0 (scratch) is never
    handed out.

    Refcounts are what make prefix sharing (serving/tier/prefix_cache.py)
    safe: a block holding a shared system-prompt's K/V is referenced by
    every live request reading it PLUS the cache's own residency reference,
    and only returns to the free list when the LAST reference releases it.
    ``allocate`` hands blocks out at refcount 1; ``free``/``release`` are
    the same operation (decrement, recycle at zero) so pre-sharing callers
    keep their exact semantics."""

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError(f'need >= 2 blocks (1 scratch), got '
                             f'{num_blocks}')
        self.num_blocks = int(num_blocks)
        self._free = list(range(self.num_blocks - 1, 0, -1))  # pop() -> 1..
        self._refs = {}               # live block id -> refcount >= 1
        self._lock = threading.Lock()

    @property
    def capacity(self):
        return self.num_blocks - 1

    @property
    def available(self):
        with self._lock:
            return len(self._free)

    @property
    def used(self):
        return self.capacity - self.available

    def refcount(self, block_id):
        """Live references on ``block_id`` (0 = on the free list)."""
        with self._lock:
            return self._refs.get(int(block_id), 0)

    def allocate(self, n):
        """n block ids at refcount 1, or raise :class:`OutOfBlocks`
        (nothing allocated)."""
        n = int(n)
        with self._lock:
            if n > len(self._free):
                raise OutOfBlocks(n, len(self._free))
            ids = [self._free.pop() for _ in range(n)]
            for b in ids:
                self._refs[b] = 1
            return ids

    def retain(self, block_ids):
        """Add one reference per block (sharing an already-live block)."""
        with self._lock:
            for b in block_ids:
                b = int(b)
                if b not in self._refs:
                    raise ValueError(f'retain of non-live block {b}')
                self._refs[b] += 1

    def release(self, block_ids):
        """Drop one reference per block; blocks reaching zero return to the
        free list. Releasing a non-live block raises (double-free)."""
        with self._lock:
            for b in block_ids:
                b = int(b)
                if b == SCRATCH_BLOCK:
                    raise ValueError('freeing the scratch block')
                if b not in self._refs:
                    raise ValueError(f'double free of block {b}')
                self._refs[b] -= 1
                if self._refs[b] == 0:
                    del self._refs[b]
                    self._free.append(b)

    # exclusive ownership (refcount 1) makes free == release; kept as the
    # name the pre-sharing callers (engine/scheduler/tests) use
    free = release


class BlockTable:
    """One request's cache blocks, in sequence order. ``context_len`` is the
    number of cached tokens (prompt + generated so far)."""

    __slots__ = ('blocks', 'block_size', 'context_len', 'cached_len')

    def __init__(self, blocks, block_size, cached_len=0):
        self.blocks = list(blocks)
        self.block_size = int(block_size)
        self.context_len = 0
        # tokens at the FRONT of the table already filled by shared
        # prefix-cache blocks (always a whole-block multiple); this request
        # must never write positions < cached_len — they belong to every
        # other request sharing those blocks
        self.cached_len = int(cached_len)

    @property
    def capacity_tokens(self):
        return len(self.blocks) * self.block_size

    def slot_for(self, position):
        """(block_id, offset) holding token ``position``."""
        if position >= self.capacity_tokens:
            raise IndexError(
                f'position {position} beyond the table\'s '
                f'{self.capacity_tokens} reserved token slots')
        return (self.blocks[position // self.block_size],
                position % self.block_size)

    def padded(self, max_blocks_per_seq):
        """Block ids padded to the engine-wide table width with scratch."""
        if len(self.blocks) > max_blocks_per_seq:
            raise ValueError(
                f'{len(self.blocks)} blocks exceed max_blocks_per_seq='
                f'{max_blocks_per_seq}')
        return self.blocks + [SCRATCH_BLOCK] * (max_blocks_per_seq
                                                - len(self.blocks))


class KVCachePool:
    """Per-layer paged K/V arrays + the shared allocator.

    ``max_blocks_per_seq`` fixes the batched block-table width — and with
    it ``padded_context = max_blocks_per_seq * block_size``, the key extent
    every attention read uses. The bitwise contract with whole-sequence
    decode holds at exactly that padded length (see ops/nn_ops.py).
    """

    def __init__(self, block_size=None, num_blocks=None,
                 max_blocks_per_seq=None, dtype='float32', kv_dtype=None):
        self.block_size = int(block_size or DEFAULT_BLOCK_SIZE)
        self.num_blocks = int(num_blocks or DEFAULT_MAX_BLOCKS)
        self.max_blocks_per_seq = int(max_blocks_per_seq or 8)
        kv_dtype = kv_dtype or 'f32'
        if kv_dtype not in KV_PAYLOAD_DTYPES:
            raise ValueError(
                f'kv_dtype={kv_dtype!r} is not supported; supported values: '
                + ', '.join(repr(c) for c in KV_PAYLOAD_DTYPES))
        self.kv_dtype = kv_dtype
        # 'f32' keeps honoring the legacy ``dtype`` arg so the default path
        # allocates EXACTLY the arrays it always did (bitwise contract)
        self.dtype = dtype if kv_dtype == 'f32' else KV_PAYLOAD_DTYPES[
            kv_dtype]
        self.allocator = BlockAllocator(self.num_blocks)
        self._layers = {}          # layer idx -> [k_pages, v_pages]
        self._scales = {}          # int8 only: layer -> [k_scales, v_scales]

    @property
    def padded_context(self):
        return self.max_blocks_per_seq * self.block_size

    @property
    def num_layers(self):
        return len(self._layers)

    def new_table(self, total_tokens):
        """Allocate a table holding ``total_tokens`` (prompt + budget).
        Raises OutOfBlocks when the pool cannot cover it right now."""
        nb = -(-int(total_tokens) // self.block_size)
        if nb > self.max_blocks_per_seq:
            raise InvalidRequest(
                f'{total_tokens} tokens need {nb} blocks > '
                f'max_blocks_per_seq={self.max_blocks_per_seq}')
        return BlockTable(self.allocator.allocate(nb), self.block_size)

    def free_table(self, table):
        if table.blocks:
            self.allocator.free(table.blocks)
            table.blocks = []

    def ensure_layer(self, layer, n_heads, head_dim):
        if layer not in self._layers:
            import jax.numpy as jnp
            shape = (n_heads, self.num_blocks, self.block_size, head_dim)
            self._layers[layer] = [jnp.zeros(shape, self.dtype),
                                   jnp.zeros(shape, self.dtype)]
            if self.kv_dtype == 'int8':
                # one f32 scale per (head, position) row; zero-init means
                # unwritten rows (incl. the scratch block) dequantize to
                # exact zeros — the masking contract at a new dtype
                self._scales[layer] = [jnp.zeros(shape[:3], 'float32'),
                                       jnp.zeros(shape[:3], 'float32')]
        return self._layers[layer]

    def pages(self, layer):
        return self._layers[layer]

    def scales(self, layer):
        """int8 pools: [k_scales, v_scales] each (H, NB, BS) f32; ``None``
        for f32/bf16 pools (payload is self-describing)."""
        return self._scales.get(layer)

    def _encode_rows(self, vals):
        """f32 rows (H, ..., D) → (payload at the storage dtype, row scales
        (H, ...) f32 or ``None``). The f32 branch returns its input object
        untouched — the default path must stay bitwise-identical."""
        if self.kv_dtype == 'f32':
            return vals, None
        import jax.numpy as jnp
        if self.kv_dtype == 'bf16':
            return jnp.asarray(vals).astype(jnp.bfloat16), None
        from ...parallel.quant_collectives import rowwise_quantize
        return rowwise_quantize(vals)

    def bytes_in_hbm(self):
        """Resident pool bytes across all allocated layers: payload arrays
        plus (int8) their scale arrays — the kv_cache_bytes_in_hbm gauge."""
        total = 0
        for arrs in self._layers.values():
            total += sum(int(a.nbytes) for a in arrs)
        for arrs in self._scales.values():
            total += sum(int(a.nbytes) for a in arrs)
        return total

    def write_prefill(self, layer, table, k, v):
        """Write the prompt's K/V rows. ``k``/``v``: (H, L, D) — the bucket-
        padded projections; rows are written for ``ceil(context/bs)`` whole
        blocks (tail rows inside the last block are masked garbage until
        decode overwrites them)."""
        import jax.numpy as jnp
        h, L, d = k.shape
        pages = self.ensure_layer(layer, h, d)
        nb_w = min(-(-table.context_len // self.block_size),
                   len(table.blocks))
        target = nb_w * self.block_size
        if L < target:
            pad = ((0, 0), (0, target - L), (0, 0))
            k = jnp.pad(k, pad)
            v = jnp.pad(v, pad)
        ids = np.asarray(table.blocks[:nb_w], np.int32)
        kb = k[:, :target].reshape(h, nb_w, self.block_size, d)
        vb = v[:, :target].reshape(h, nb_w, self.block_size, d)
        kb, ks = self._encode_rows(kb)
        vb, vs = self._encode_rows(vb)
        pages[0] = _scatter_blocks(pages[0], ids, kb)
        pages[1] = _scatter_blocks(pages[1], ids, vb)
        if ks is not None:
            sc = self._scales[layer]
            sc[0] = _scatter_block_scales(sc[0], ids, ks)
            sc[1] = _scatter_block_scales(sc[1], ids, vs)

    def write_tokens(self, layer, block_ids, offsets, k, v):
        """One decode step's K/V: ``k``/``v`` (H, S, D) written at
        (block_ids[s], offsets[s]) per slot. Inactive slots point at the
        scratch block."""
        h, s, d = k.shape
        pages = self.ensure_layer(layer, h, d)
        ids = np.asarray(block_ids, np.int32)
        offs = np.asarray(offsets, np.int32)
        k, ks = self._encode_rows(k)
        v, vs = self._encode_rows(v)
        pages[0] = _scatter_tokens(pages[0], ids, offs, k)
        pages[1] = _scatter_tokens(pages[1], ids, offs, v)
        if ks is not None:
            sc = self._scales[layer]
            sc[0] = _scatter_token_scales(sc[0], ids, offs, ks)
            sc[1] = _scatter_token_scales(sc[1], ids, offs, vs)

    # -- whole-block transfer (serving/tier/disagg.py handoff) -------------
    def read_blocks(self, layer, block_ids):
        """Gather whole blocks as host arrays: ``(k, v)`` each
        (H, nb, block_size, D). The disaggregation payload format — a
        prefill replica reads its finished blocks out, a decode replica
        writes them into its own pool ids."""
        ids = np.asarray(block_ids, np.int32)
        k_pages, v_pages = self._layers[layer]
        return (np.asarray(k_pages[:, ids]), np.asarray(v_pages[:, ids]))

    def read_block_scales(self, layer, block_ids):
        """int8 pools: gather the blocks' row scales as host arrays
        ``(k_scales, v_scales)`` each (H, nb, block_size) f32 — shipped
        beside :meth:`read_blocks` payloads so a same-dtype receiver can
        scatter them back byte-exact. ``None`` for f32/bf16 pools."""
        if layer not in self._scales:
            return None
        ids = np.asarray(block_ids, np.int32)
        ks, vs = self._scales[layer]
        return (np.asarray(ks[:, ids]), np.asarray(vs[:, ids]))

    def write_whole_blocks(self, layer, block_ids, k, v,
                           k_scale=None, v_scale=None):
        """Scatter whole blocks (the :meth:`read_blocks` shapes) into this
        pool at ``block_ids`` — the receiving half of a KV handoff or a
        host-tier reinjection.

        Dtype conversion matrix: payload already at this pool's storage
        dtype (int8 arriving WITH its scales) scatters directly —
        byte-exact, which is what makes same-dtype disagg handoff and
        spill→reinject bitwise; otherwise the incoming rows are decoded to
        f32 (using ``k_scale``/``v_scale`` when the sender was int8) and
        re-encoded at this pool's dtype."""
        h, nb, bs, d = k.shape
        if bs != self.block_size:
            raise InvalidRequest(
                f'handoff block_size {bs} != pool block_size '
                f'{self.block_size}')
        pages = self.ensure_layer(layer, h, d)
        ids = np.asarray(block_ids, np.int32)
        import jax.numpy as jnp
        k = jnp.asarray(k)
        v = jnp.asarray(v)
        same = (k.dtype == jnp.dtype(self.dtype)
                and (self.kv_dtype != 'int8' or k_scale is not None))
        if same:
            ks, vs = k_scale, v_scale
        else:
            if k_scale is not None:      # sender was int8: decode first
                from ...parallel.quant_collectives import rowwise_dequantize
                k = rowwise_dequantize(k, k_scale)
                v = rowwise_dequantize(v, v_scale)
            k, ks = self._encode_rows(k.astype(jnp.float32))
            v, vs = self._encode_rows(v.astype(jnp.float32))
        pages[0] = _scatter_blocks(pages[0], ids, k)
        pages[1] = _scatter_blocks(pages[1], ids, v)
        if self.kv_dtype == 'int8':
            sc = self._scales[layer]
            sc[0] = _scatter_block_scales(sc[0], ids, jnp.asarray(ks))
            sc[1] = _scatter_block_scales(sc[1], ids, jnp.asarray(vs))

    # -- observability -----------------------------------------------------
    def utilization(self):
        return self.allocator.used / max(self.allocator.capacity, 1)


class CacheContext:
    """The duck-typed ``cache=`` object MultiHeadAttention calls into
    (models/bert.py). One context per model forward; each attention layer's
    ``attend(q, k, v, sm_scale=)`` call consumes the next layer index.

    mode='prefill': q/k/v are (1, H, Lb, D) for one bucket-padded prompt —
    K/V are written into the request's blocks, attention runs causal over
    the paged view (`paged_prefill_attention`).

    mode='decode': q/k/v are (S, H, 1, D), one token per slot — K/V land at
    each slot's next position, attention reads through the batched block
    tables (`paged_attention`) at fixed shape.

    mode='decode' with ``window`` K > 1 (speculative verify — the (S, K)
    step): q/k/v are (S, H, K, D); each slot feeds ``fed_counts[s]`` ≤ K
    real tokens at positions context_len-1 .. context_len-1+f-1 and the
    remaining K-f padded lanes write to the scratch block (harmless by the
    masking contract above). ``context_lens[s]`` is still the extent of
    fed ROW 0; `paged_attention`'s multi-query form gives row j the causal
    staircase extent context_lens + j.
    """

    def __init__(self, pool, mode, tables, context_lens=None,
                 fed_counts=None, window=1):
        self.pool = pool
        self.mode = mode
        self.tables = tables          # prefill: [BlockTable]; decode: list
        self.context_lens = context_lens
        self.window = int(window)
        self._layer = 0
        if mode == 'decode':
            if fed_counts is None:
                fed_counts = [1 if t is not None else 0 for t in tables]
            ids, offs, padded = [], [], []
            for t, c, f in zip(tables, context_lens, fed_counts):
                if t is None:                       # inactive slot
                    ids.extend([SCRATCH_BLOCK] * self.window)
                    offs.extend([0] * self.window)
                    padded.append([SCRATCH_BLOCK]
                                  * pool.max_blocks_per_seq)
                    continue
                base = int(c) - 1          # first token written this step
                for j in range(self.window):
                    if j < int(f):
                        b, o = t.slot_for(base + j)
                    else:                  # padded lane: scratch write
                        b, o = SCRATCH_BLOCK, 0
                    ids.append(b)
                    offs.append(o)
                padded.append(t.padded(pool.max_blocks_per_seq))
            self._write_ids = np.asarray(ids, np.int32)
            self._write_offs = np.asarray(offs, np.int32)
            self._batched_tables = np.asarray(padded, np.int32)
            self._ctx = np.asarray(
                [max(int(c), 1) for c in context_lens], np.int32)

    def _scale_inputs(self, layer):
        """Extra dispatch inputs for int8 pools ({} otherwise — the f32/bf16
        dispatch must stay slot-for-slot what it was before quantization)."""
        sc = self.pool.scales(layer)
        if sc is None:
            return {}
        return {'k_scales': sc[0], 'v_scales': sc[1]}

    def attend(self, q, k, v, sm_scale=1.0):
        from ...dygraph.tape import Tensor, dispatch_op
        layer = self._layer
        self._layer += 1
        kv = k.value if isinstance(k, Tensor) else k
        vv = v.value if isinstance(v, Tensor) else v
        if self.mode == 'prefill':
            table = self.tables[0]
            # (1, H, L, D) -> (H, L, D) rows for the block scatter
            self.pool.write_prefill(layer, table, kv[0], vv[0])
            k_pages, v_pages = self.pool.pages(layer)
            bt = np.asarray([table.padded(self.pool.max_blocks_per_seq)],
                            np.int32)
            inputs = {'q': q, 'k': k, 'v': v, 'k_pages': k_pages,
                      'v_pages': v_pages, 'block_tables': bt}
            inputs.update(self._scale_inputs(layer))
            return dispatch_op('paged_prefill_attention', inputs,
                               {'sm_scale': float(sm_scale)})
        if self.window > 1:
            # multi-token decode (speculative verify): (S, H, K, D) ->
            # (H, S·K, D) rows, slot-major, matching the flattened write
            # coordinates built above; q stays rank-4 for the multi-query
            # paged_attention read
            s, h, k_w, d = kv.shape
            self.pool.write_tokens(
                layer, self._write_ids, self._write_offs,
                kv.transpose(1, 0, 2, 3).reshape(h, s * k_w, d),
                vv.transpose(1, 0, 2, 3).reshape(h, s * k_w, d))
            k_pages, v_pages = self.pool.pages(layer)
            inputs = {'q': q, 'k_pages': k_pages, 'v_pages': v_pages,
                      'block_tables': self._batched_tables,
                      'context_lens': self._ctx}
            inputs.update(self._scale_inputs(layer))
            return dispatch_op('paged_attention', inputs,
                               {'sm_scale': float(sm_scale)})
        # decode: (S, H, 1, D) -> (H, S, D) token rows
        self.pool.write_tokens(layer, self._write_ids, self._write_offs,
                               kv[:, :, 0].transpose(1, 0, 2),
                               vv[:, :, 0].transpose(1, 0, 2))
        k_pages, v_pages = self.pool.pages(layer)
        q3 = dispatch_op('reshape', {'x': q},
                         {'shape': [q.shape[0], q.shape[1], q.shape[3]]})
        inputs = {'q': q3, 'k_pages': k_pages, 'v_pages': v_pages,
                  'block_tables': self._batched_tables,
                  'context_lens': self._ctx}
        inputs.update(self._scale_inputs(layer))
        out = dispatch_op('paged_attention', inputs,
                          {'sm_scale': float(sm_scale)})
        return dispatch_op('reshape', {'x': out},
                           {'shape': [q.shape[0], q.shape[1], 1,
                                      q.shape[3]]})
