"""Stateful decode engine: paged KV cache + slot-based continuous batching
+ streaming generation (docs/SERVING.md "Stateful decode").

Layered on the PR-4 serving stack the way the ROADMAP's item 2 describes:

- :class:`KVCachePool` (kv_cache.py) — fixed-size cache blocks, free-list
  allocator, per-request :class:`BlockTable`s; sized once at engine start
  (`PADDLE_TPU_DECODE_{SLOTS,BLOCK_SIZE,MAX_BLOCKS}`).
- :class:`DecodeEngine` (engine.py) — prefill through a prompt bucket
  ladder writes K/V into cache blocks; decode steps all S slots in
  lockstep at ONE fixed shape through the ``paged_attention`` op, so the
  compile count is independent of generated length.
- :class:`DecodeScheduler` (scheduler.py) — slot-based continuous
  batching: new requests admitted into freed slots every step (vs
  drain-then-refill), bounded-queue backpressure, waiting deadlines,
  graceful drain; per-request :class:`GenerationStream` token streams.
- HTTP: ``POST /generate`` on :class:`serving.ServingServer` streams
  tokens as chunked NDJSON (server.py).

Quick start::

    from paddle_tpu import serving
    from paddle_tpu.models.causal_lm import CausalLMConfig, TransformerLM

    engine = serving.DecodeEngine(TransformerLM(cfg), slots=8)
    engine.warmup()
    with serving.DecodeScheduler(engine) as sched:
        for tok in sched.submit([1, 17, 4], max_new_tokens=32):
            print(tok)                     # streams as they decode
"""
from __future__ import annotations

from .kv_cache import (BlockAllocator, BlockTable, CacheContext, KVCachePool,
                       DEFAULT_BLOCK_SIZE, DEFAULT_MAX_BLOCKS, DEFAULT_SLOTS)
from .engine import DecodeEngine
from .sampling import SamplingParams, TokenSampler, derive_stream_seed
from .drafter import NGramDrafter, DraftModelDrafter, build_drafter
from .scheduler import DecodeScheduler, GenerationStream

__all__ = ['BlockAllocator', 'BlockTable', 'CacheContext', 'KVCachePool',
           'DecodeEngine', 'DecodeScheduler', 'GenerationStream',
           'SamplingParams', 'TokenSampler', 'derive_stream_seed',
           'NGramDrafter', 'DraftModelDrafter', 'build_drafter',
           'DEFAULT_SLOTS', 'DEFAULT_BLOCK_SIZE', 'DEFAULT_MAX_BLOCKS']
