"""Per-request sampling params + deterministic, replayable token sampling.

Two contracts (docs/SERVING.md "Sampling & speculative decode"):

**Typed validation.** :meth:`SamplingParams.validate` is the single parser
every entry point (scheduler ``submit``, HTTP handler, router) funnels
through — a bad value raises :class:`InvalidRequest` NAMING the field, so
the HTTP layer maps it to a 400 that tells the client what to fix instead
of silently dropping the key.

**Bitwise replay.** A sampled stream is a pure function of
``(request_id-or-seed, params, prompt, model weights)``:

- the stream seed is ``params.seed`` when pinned, else derived from the
  restart-safe ``request_id`` (sha256 → 63 bits — request ids are
  free-form client strings, not guaranteed hex);
- the seed feeds the same :class:`~paddle_tpu.core.random.KeyGenerator`
  machinery the rest of the framework uses (base ``jax.random.PRNGKey``),
  and token ``i`` of the stream draws from ``fold_in(base, i)`` — the
  draw depends on the token INDEX, never on wall clock, slot id, batch
  composition, or how many requests ran before;
- filtering (temperature → top-k → top-p) and the inverse-CDF draw run in
  float64 numpy with a stable sort, so the picked token is exactly
  reproducible across processes (the replay drill in
  tests/framework/test_spec_decode.py restarts a subprocess to prove it).

``temperature == 0`` (the default) is GREEDY: a plain argmax with no key
material touched — the pre-existing bitwise decode contract is unchanged.
"""
from __future__ import annotations

import hashlib

import numpy as np

from ..errors import InvalidRequest

__all__ = ['SamplingParams', 'TokenSampler', 'derive_stream_seed']

_FIELDS = ('temperature', 'top_k', 'top_p', 'seed')


class SamplingParams:
    """Validated per-request sampling knobs.

    - ``temperature``: 0 = greedy (exact argmax, bitwise-identical to the
      pre-sampling engine); > 0 scales logits before the draw.
    - ``top_k``: 0 = off; k > 0 keeps only the k highest-logit tokens.
    - ``top_p``: 1.0 = off; p ∈ (0, 1] keeps the smallest prefix of the
      descending-probability ordering whose mass reaches p (always ≥ 1
      token).
    - ``seed``: optional explicit stream seed; when None the stream seeds
      from the request_id (see :func:`derive_stream_seed`).
    """

    __slots__ = _FIELDS

    def __init__(self, temperature=0.0, top_k=0, top_p=1.0, seed=None):
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = seed if seed is None else int(seed)

    @property
    def greedy(self):
        return self.temperature == 0.0

    @classmethod
    def validate(cls, obj):
        """Parse ``obj`` (None | dict | SamplingParams) into a validated
        instance, or raise :class:`InvalidRequest` naming the offending
        field. Unknown dict keys raise too — a typo'd knob must not be
        silently ignored."""
        if obj is None:
            return cls()
        if isinstance(obj, cls):
            d = {f: getattr(obj, f) for f in _FIELDS}
        elif isinstance(obj, dict):
            unknown = sorted(set(obj) - set(_FIELDS))
            if unknown:
                raise InvalidRequest(
                    f'unknown sampling field(s): {", ".join(unknown)}; '
                    f'supported: {", ".join(_FIELDS)}')
            d = dict(obj)
        else:
            raise InvalidRequest(
                f'sampling must be a dict or SamplingParams, got '
                f'{type(obj).__name__}')

        def _num(name, default, kind=float):
            val = d.get(name, default)
            if val is None:
                val = default
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                raise InvalidRequest(f'{name} must be a number, got '
                                     f'{type(val).__name__}')
            if kind is int and float(val) != int(val):
                raise InvalidRequest(f'{name} must be an integer, got '
                                     f'{val!r}')
            return kind(val)

        temperature = _num('temperature', 0.0)
        if not np.isfinite(temperature) or temperature < 0:
            raise InvalidRequest(
                f'temperature must be >= 0 and finite, got {temperature}')
        top_k = _num('top_k', 0, int)
        if top_k < 0:
            raise InvalidRequest(f'top_k must be >= 0, got {top_k}')
        top_p = _num('top_p', 1.0)
        if not 0.0 < top_p <= 1.0:
            raise InvalidRequest(f'top_p must be in (0, 1], got {top_p}')
        seed = d.get('seed')
        if seed is not None:
            if isinstance(seed, bool) or not isinstance(seed, int):
                raise InvalidRequest(
                    f'seed must be an integer, got {type(seed).__name__}')
            seed = int(seed) & ((1 << 63) - 1)
        return cls(temperature, top_k, top_p, seed)

    def to_dict(self):
        return {f: getattr(self, f) for f in _FIELDS}

    def __repr__(self):
        return (f'SamplingParams(temperature={self.temperature}, '
                f'top_k={self.top_k}, top_p={self.top_p}, '
                f'seed={self.seed})')


def derive_stream_seed(request_id, seed=None):
    """The stream seed: an explicit ``seed`` wins; otherwise hash the
    restart-safe ``request_id`` down to 63 bits (PRNGKey-safe). sha256 is
    stable across processes and Python versions — ``hash()`` is not."""
    if seed is not None:
        return int(seed) & ((1 << 63) - 1)
    digest = hashlib.sha256(str(request_id).encode('utf-8')).digest()
    return int.from_bytes(digest[:8], 'big') & ((1 << 63) - 1)


class TokenSampler:
    """Deterministic per-request sampler over raw logits rows.

    One instance per request; ``sample(row, index)`` is a pure function of
    (stream seed, params, row bits, index) — the replay contract above."""

    def __init__(self, params, request_id):
        from ...core.random import KeyGenerator
        self.params = params
        self.stream_seed = derive_stream_seed(request_id, params.seed)
        # the framework's own key machinery: base = PRNGKey(seed), built
        # lazily (KeyGenerator's import-time discipline)
        self._keygen = KeyGenerator(self.stream_seed)

    def sample(self, row, index):
        """Draw generated-token ``index`` (0-based) of this stream from the
        logits ``row`` (V,). Greedy params short-circuit to argmax."""
        import jax
        p = self.params
        row = np.asarray(row)
        if p.greedy:
            return int(row.argmax())
        logits = row.astype(np.float64) / p.temperature
        # stable descending order: ties broken by token id, ascending —
        # deterministic regardless of the backend's argsort implementation
        order = np.argsort(-logits, kind='stable')
        if p.top_k > 0:
            order = order[:p.top_k]
        shifted = logits[order] - logits[order[0]]
        probs = np.exp(shifted)
        probs /= probs.sum()
        if p.top_p < 1.0:
            # keep the minimal prefix reaching mass top_p (≥ 1 token):
            # token j survives iff the mass BEFORE it is still < top_p
            before = np.cumsum(probs) - probs
            keep = before < p.top_p
            order, probs = order[keep], probs[keep]
            probs = probs / probs.sum()
        key = jax.random.fold_in(self._keygen.base_key(), int(index))
        u = float(jax.random.uniform(key, (), dtype=np.float32))
        idx = int(np.searchsorted(np.cumsum(probs), u, side='right'))
        return int(order[min(idx, len(order) - 1)])
