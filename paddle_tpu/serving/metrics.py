"""Serving metric handles (always-on, unlike training telemetry).

Training instrumentation guards on ``observability._ENABLED`` because the
eager dispatch path is ~10 µs/op; the serving path runs one device call per
*batch* (ms-scale), so a handful of counter increments per request is noise.
More importantly the HTTP ``/metrics`` endpoint must work out of the box —
an operator scraping a serving box should not need PADDLE_TPU_TELEMETRY=1.
So serving records straight into :data:`observability.registry` and shows up
in both its exports alongside whatever the training-side telemetry collected.

Every handle here is a :class:`_LazyMetric` proxy that re-resolves through
the registry ON EACH USE rather than capturing the metric object at import:
``registry.reset()`` (tests, telemetry teardown) drops all metric objects,
and a captured handle would keep counting into an orphan that no longer
appears in any export. The resolve is one dict lookup — noise at ms-scale.

Metric catalog (docs/OBSERVABILITY.md has the full table):

- request lifecycle counters: accepted / rejected_overload / rejected_invalid
  / completed / failed / deadline_missed
- serving_queue_depth gauge (sampled at submit/dequeue)
- serving_queue_wait_seconds / serving_compute_seconds histograms — the
  queue-wait vs compute split is THE batching-knob tuning signal
- serving_batch_rows / serving_padding_waste_ratio histograms — how full the
  coalesced batches are and how much of each padded bucket is thrown away
- per-bucket gauges/counters: serving_bucket_runs (label bucket),
  serving_bucket_compiled, serving_bucket_compile_seconds (warmup/first-use)
"""
from __future__ import annotations

from ..observability import registry

# padding waste is a ratio in [0, 1): linear buckets
_WASTE_BOUNDS = tuple(i / 10.0 for i in range(1, 10))
# batch row counts: powers of two cover any sane bucket ladder
_ROWS_BOUNDS = tuple(float(2 ** i) for i in range(11))


class _LazyMetric:
    """Registry-resolving proxy: same call surface as Counter/Gauge/Histogram
    (inc/set/observe/labels/value), but survives registry.reset()."""

    __slots__ = ('_kind', '_name', '_help', '_bounds')

    def __init__(self, kind, name, help, bounds=None):
        self._kind = kind
        self._name = name
        self._help = help
        self._bounds = bounds

    def _metric(self):
        if self._kind == 'counter':
            return registry.counter(self._name, self._help)
        if self._kind == 'gauge':
            return registry.gauge(self._name, self._help)
        if self._bounds is not None:
            return registry.histogram(self._name, self._help, self._bounds)
        return registry.histogram(self._name, self._help)

    def inc(self, amount=1.0):
        self._metric().inc(amount)

    def set(self, value):
        self._metric().set(value)

    def observe(self, value):
        self._metric().observe(value)

    def labels(self, **labels):
        return self._metric().labels(**labels)

    @property
    def value(self):
        return self._metric().value


requests_accepted = _LazyMetric(
    'counter', 'serving_requests_accepted',
    'requests admitted to the serving queue')
requests_rejected_overload = _LazyMetric(
    'counter', 'serving_requests_rejected_overload',
    'requests rejected by bounded-queue backpressure (Overloaded)')
requests_rejected_invalid = _LazyMetric(
    'counter', 'serving_requests_rejected_invalid',
    'requests rejected by pre-enqueue validation (InvalidRequest)')
requests_completed = _LazyMetric(
    'counter', 'serving_requests_completed',
    'requests answered with results')
requests_failed = _LazyMetric(
    'counter', 'serving_requests_failed',
    'requests failed by an engine/runtime error after admission')
requests_deadline_missed = _LazyMetric(
    'counter', 'serving_requests_deadline_missed',
    'requests dropped because their deadline expired in the queue')

queue_depth = _LazyMetric(
    'gauge', 'serving_queue_depth',
    'requests waiting in the micro-batcher queue')

queue_wait_seconds = _LazyMetric(
    'histogram', 'serving_queue_wait_seconds',
    'enqueue → batch-execution wait per request')
compute_seconds = _LazyMetric(
    'histogram', 'serving_compute_seconds',
    'device call duration per coalesced batch (by padded bucket)')
batch_rows = _LazyMetric(
    'histogram', 'serving_batch_rows',
    'real (unpadded) rows per executed batch', bounds=_ROWS_BOUNDS)
padding_waste_ratio = _LazyMetric(
    'histogram', 'serving_padding_waste_ratio',
    'fraction of the padded bucket that was padding, per executed batch',
    bounds=_WASTE_BOUNDS)

bucket_runs = _LazyMetric(
    'counter', 'serving_bucket_runs', 'executed batches per bucket size')
bucket_compiled = _LazyMetric(
    'gauge', 'serving_bucket_compiled',
    '1 once the bucket shape has been compiled (warmup or first use)')
bucket_compile_seconds = _LazyMetric(
    'gauge', 'serving_bucket_compile_seconds',
    'wall seconds of the bucket\'s first (compiling) run')
http_responses = _LazyMetric(
    'counter', 'serving_http_responses',
    'HTTP front-end responses by status code')

# -- circuit breaker (serving/breaker.py) ----------------------------------
# state encoding: 0 = closed, 1 = half-open (probing), 2 = open (tripped)

breaker_state = _LazyMetric(
    'gauge', 'serving_breaker_state',
    'predict-path circuit breaker state (0 closed / 1 half-open / 2 open)')
breaker_trips = _LazyMetric(
    'counter', 'serving_breaker_trips',
    'predict-path breaker trips (consecutive-failure threshold or failed '
    'probe)')
breaker_rejected = _LazyMetric(
    'counter', 'serving_breaker_rejected',
    'requests rejected fast with EngineUnhealthy while the breaker was open')
breaker_probes = _LazyMetric(
    'counter', 'serving_breaker_probes',
    'half-open probe windows opened after the breaker cooldown')

PREDICT_BREAKER_METRICS = {'state': breaker_state, 'trips': breaker_trips,
                           'rejected': breaker_rejected,
                           'probes': breaker_probes}


# -- stateful decode engine (serving/decode/, docs/SERVING.md) -------------
# Same always-on discipline as the rest of serving: decode steps are
# ms-scale device calls, and /metrics on a generation server must work
# without PADDLE_TPU_TELEMETRY.

# slot occupancy is a ratio in [0, 1]: linear buckets
_OCCUPANCY_BOUNDS = tuple(i / 10.0 for i in range(1, 10))

decode_requests_accepted = _LazyMetric(
    'counter', 'decode_requests_accepted',
    'generation requests admitted to the decode queue')
decode_requests_completed = _LazyMetric(
    'counter', 'decode_requests_completed',
    'generations finished (eos or token budget)')
decode_requests_failed = _LazyMetric(
    'counter', 'decode_requests_failed',
    'generations failed by an engine/runtime error after admission')
decode_requests_rejected_overload = _LazyMetric(
    'counter', 'decode_requests_rejected_overload',
    'generation requests rejected by bounded-queue backpressure')
decode_requests_rejected_invalid = _LazyMetric(
    'counter', 'decode_requests_rejected_invalid',
    'generation requests rejected by pre-enqueue validation')
decode_requests_deadline_missed = _LazyMetric(
    'counter', 'decode_requests_deadline_missed',
    'generation requests dropped because their deadline expired while '
    'waiting for a slot')
decode_queue_depth = _LazyMetric(
    'gauge', 'decode_queue_depth',
    'generation requests waiting for a decode slot')

decode_slots_total = _LazyMetric(
    'gauge', 'decode_slots_total', 'configured lockstep decode slots (S)')
decode_slots_active = _LazyMetric(
    'gauge', 'decode_slots_active',
    'slots holding a live generation, sampled each decode step')
decode_slot_occupancy = _LazyMetric(
    'histogram', 'decode_slot_occupancy',
    'active/total slot ratio per decode step — the continuous-batching '
    'efficiency signal', bounds=_OCCUPANCY_BOUNDS)

decode_cache_blocks_total = _LazyMetric(
    'gauge', 'decode_cache_blocks_total',
    'allocatable KV-cache blocks (pool size minus the scratch block)')
decode_cache_blocks_used = _LazyMetric(
    'gauge', 'decode_cache_blocks_used',
    'KV-cache blocks currently reserved by live generations')

decode_prefill_seconds = _LazyMetric(
    'histogram', 'decode_prefill_seconds',
    'wall seconds per prompt prefill (bucket-padded, one per admission)')
decode_step_seconds = _LazyMetric(
    'histogram', 'decode_step_seconds',
    'wall seconds per lockstep decode step (all S slots) — with '
    'decode_prefill_seconds this is the prefill-vs-decode time split')
decode_steps = _LazyMetric(
    'counter', 'decode_steps', 'lockstep decode steps executed')
decode_tokens_generated = _LazyMetric(
    'counter', 'decode_tokens_generated',
    'tokens emitted to generation streams (rate = tokens/s)')
decode_prefill_compiles = _LazyMetric(
    'counter', 'decode_prefill_compiles',
    'prefill bucket shapes compiled (bounded by the prompt ladder length)')

decode_breaker_state = _LazyMetric(
    'gauge', 'decode_breaker_state',
    'decode-path circuit breaker state (0 closed / 1 half-open / 2 open)')
decode_breaker_trips = _LazyMetric(
    'counter', 'decode_breaker_trips',
    'decode-path breaker trips (consecutive-failure threshold or failed '
    'probe)')
decode_breaker_rejected = _LazyMetric(
    'counter', 'decode_breaker_rejected',
    'generation requests rejected fast with EngineUnhealthy while the '
    'decode breaker was open')
decode_breaker_probes = _LazyMetric(
    'counter', 'decode_breaker_probes',
    'half-open probe windows opened after the decode breaker cooldown')

DECODE_BREAKER_METRICS = {'state': decode_breaker_state,
                          'trips': decode_breaker_trips,
                          'rejected': decode_breaker_rejected,
                          'probes': decode_breaker_probes}
