"""Serving metric handles (always-on, unlike training telemetry).

Training instrumentation guards on ``observability._ENABLED`` because the
eager dispatch path is ~10 µs/op; the serving path runs one device call per
*batch* (ms-scale), so a handful of counter increments per request is noise.
More importantly the HTTP ``/metrics`` endpoint must work out of the box —
an operator scraping a serving box should not need PADDLE_TPU_TELEMETRY=1.
So serving records straight into :data:`observability.registry` and shows up
in both its exports alongside whatever the training-side telemetry collected.

Every handle here is a :class:`_LazyMetric` proxy that re-resolves through
the registry ON EACH USE rather than capturing the metric object at import:
``registry.reset()`` (tests, telemetry teardown) drops all metric objects,
and a captured handle would keep counting into an orphan that no longer
appears in any export. The resolve is one dict lookup — noise at ms-scale.

Metric catalog (docs/OBSERVABILITY.md has the full table):

- request lifecycle counters: accepted / rejected_overload / rejected_invalid
  / completed / failed / deadline_missed
- serving_queue_depth gauge (sampled at submit/dequeue)
- serving_queue_wait_seconds / serving_compute_seconds histograms — the
  queue-wait vs compute split is THE batching-knob tuning signal
- serving_batch_rows / serving_padding_waste_ratio histograms — how full the
  coalesced batches are and how much of each padded bucket is thrown away
- per-bucket gauges/counters: serving_bucket_runs (label bucket),
  serving_bucket_compiled, serving_bucket_compile_seconds (warmup/first-use)
"""
from __future__ import annotations

from ..observability import registry

# padding waste is a ratio in [0, 1): linear buckets
_WASTE_BOUNDS = tuple(i / 10.0 for i in range(1, 10))
# batch row counts: powers of two cover any sane bucket ladder
_ROWS_BOUNDS = tuple(float(2 ** i) for i in range(11))


class _LazyMetric:
    """Registry-resolving proxy: same call surface as Counter/Gauge/Histogram
    (inc/set/observe/labels/value), but survives registry.reset()."""

    __slots__ = ('_kind', '_name', '_help', '_bounds')

    def __init__(self, kind, name, help, bounds=None):
        self._kind = kind
        self._name = name
        self._help = help
        self._bounds = bounds

    def _metric(self):
        if self._kind == 'counter':
            return registry.counter(self._name, self._help)
        if self._kind == 'gauge':
            return registry.gauge(self._name, self._help)
        if self._bounds is not None:
            return registry.histogram(self._name, self._help, self._bounds)
        return registry.histogram(self._name, self._help)

    def inc(self, amount=1.0):
        self._metric().inc(amount)

    def set(self, value):
        self._metric().set(value)

    def observe(self, value):
        self._metric().observe(value)

    def labels(self, **labels):
        return self._metric().labels(**labels)

    @property
    def value(self):
        return self._metric().value


requests_accepted = _LazyMetric(
    'counter', 'serving_requests_accepted',
    'requests admitted to the serving queue')
requests_rejected_overload = _LazyMetric(
    'counter', 'serving_requests_rejected_overload',
    'requests rejected by bounded-queue backpressure (Overloaded)')
requests_rejected_invalid = _LazyMetric(
    'counter', 'serving_requests_rejected_invalid',
    'requests rejected by pre-enqueue validation (InvalidRequest)')
requests_completed = _LazyMetric(
    'counter', 'serving_requests_completed',
    'requests answered with results')
requests_failed = _LazyMetric(
    'counter', 'serving_requests_failed',
    'requests failed by an engine/runtime error after admission')
requests_deadline_missed = _LazyMetric(
    'counter', 'serving_requests_deadline_missed',
    'requests dropped because their deadline expired in the queue')

queue_depth = _LazyMetric(
    'gauge', 'serving_queue_depth',
    'requests waiting in the micro-batcher queue')

queue_wait_seconds = _LazyMetric(
    'histogram', 'serving_queue_wait_seconds',
    'enqueue → batch-execution wait per request')
compute_seconds = _LazyMetric(
    'histogram', 'serving_compute_seconds',
    'device call duration per coalesced batch (by padded bucket)')
batch_rows = _LazyMetric(
    'histogram', 'serving_batch_rows',
    'real (unpadded) rows per executed batch', bounds=_ROWS_BOUNDS)
padding_waste_ratio = _LazyMetric(
    'histogram', 'serving_padding_waste_ratio',
    'fraction of the padded bucket that was padding, per executed batch',
    bounds=_WASTE_BOUNDS)

bucket_runs = _LazyMetric(
    'counter', 'serving_bucket_runs', 'executed batches per bucket size')
bucket_compiled = _LazyMetric(
    'gauge', 'serving_bucket_compiled',
    '1 once the bucket shape has been compiled (warmup or first use)')
bucket_compile_seconds = _LazyMetric(
    'gauge', 'serving_bucket_compile_seconds',
    'wall seconds of the bucket\'s first (compiling) run')
http_responses = _LazyMetric(
    'counter', 'serving_http_responses',
    'HTTP front-end responses by status code')

# -- circuit breaker (serving/breaker.py) ----------------------------------
# state encoding: 0 = closed, 1 = half-open (probing), 2 = open (tripped)

breaker_state = _LazyMetric(
    'gauge', 'serving_breaker_state',
    'predict-path circuit breaker state (0 closed / 1 half-open / 2 open)')
breaker_trips = _LazyMetric(
    'counter', 'serving_breaker_trips',
    'predict-path breaker trips (consecutive-failure threshold or failed '
    'probe)')
breaker_rejected = _LazyMetric(
    'counter', 'serving_breaker_rejected',
    'requests rejected fast with EngineUnhealthy while the breaker was open')
breaker_probes = _LazyMetric(
    'counter', 'serving_breaker_probes',
    'half-open probe windows opened after the breaker cooldown')

PREDICT_BREAKER_METRICS = {'state': breaker_state, 'trips': breaker_trips,
                           'rejected': breaker_rejected,
                           'probes': breaker_probes}


# -- stateful decode engine (serving/decode/, docs/SERVING.md) -------------
# Same always-on discipline as the rest of serving: decode steps are
# ms-scale device calls, and /metrics on a generation server must work
# without PADDLE_TPU_TELEMETRY.

# slot occupancy is a ratio in [0, 1]: linear buckets
_OCCUPANCY_BOUNDS = tuple(i / 10.0 for i in range(1, 10))

decode_requests_accepted = _LazyMetric(
    'counter', 'decode_requests_accepted',
    'generation requests admitted to the decode queue')
decode_requests_completed = _LazyMetric(
    'counter', 'decode_requests_completed',
    'generations finished (eos or token budget)')
decode_requests_failed = _LazyMetric(
    'counter', 'decode_requests_failed',
    'generations failed by an engine/runtime error after admission')
decode_requests_rejected_overload = _LazyMetric(
    'counter', 'decode_requests_rejected_overload',
    'generation requests rejected by bounded-queue backpressure')
decode_requests_rejected_invalid = _LazyMetric(
    'counter', 'decode_requests_rejected_invalid',
    'generation requests rejected by pre-enqueue validation')
decode_requests_deadline_missed = _LazyMetric(
    'counter', 'decode_requests_deadline_missed',
    'generation requests dropped because their deadline expired while '
    'waiting for a slot')
decode_queue_depth = _LazyMetric(
    'gauge', 'decode_queue_depth',
    'generation requests waiting for a decode slot')

decode_slots_total = _LazyMetric(
    'gauge', 'decode_slots_total', 'configured lockstep decode slots (S)')
decode_slots_active = _LazyMetric(
    'gauge', 'decode_slots_active',
    'slots holding a live generation, sampled each decode step')
decode_slot_occupancy = _LazyMetric(
    'histogram', 'decode_slot_occupancy',
    'active/total slot ratio per decode step — the continuous-batching '
    'efficiency signal', bounds=_OCCUPANCY_BOUNDS)

decode_cache_blocks_total = _LazyMetric(
    'gauge', 'decode_cache_blocks_total',
    'allocatable KV-cache blocks (pool size minus the scratch block)')
decode_cache_blocks_used = _LazyMetric(
    'gauge', 'decode_cache_blocks_used',
    'KV-cache blocks currently reserved by live generations')

decode_prefill_seconds = _LazyMetric(
    'histogram', 'decode_prefill_seconds',
    'wall seconds per prompt prefill (bucket-padded, one per admission)')
decode_step_seconds = _LazyMetric(
    'histogram', 'decode_step_seconds',
    'wall seconds per lockstep decode step (all S slots) — with '
    'decode_prefill_seconds this is the prefill-vs-decode time split')
decode_steps = _LazyMetric(
    'counter', 'decode_steps', 'lockstep decode steps executed')
decode_tokens_generated = _LazyMetric(
    'counter', 'decode_tokens_generated',
    'tokens emitted to generation streams (rate = tokens/s)')
decode_prefill_compiles = _LazyMetric(
    'counter', 'decode_prefill_compiles',
    'prefill bucket shapes compiled (bounded by the prompt ladder length)')

# speculative decoding (engine.spec_step + scheduler verify loop); accept
# length per round is a small integer — linear buckets up to the window
_ACCEPT_BOUNDS = tuple(float(i) for i in range(9))

decode_spec_rounds = _LazyMetric(
    'counter', 'decode_spec_rounds',
    'speculative (S, k) verify steps executed (each replaces up to k '
    'lockstep steps)')
decode_spec_draft_tokens = _LazyMetric(
    'counter', 'decode_spec_draft_tokens',
    'draft tokens proposed to verify rounds across all slots')
decode_spec_accepted_tokens = _LazyMetric(
    'counter', 'decode_spec_accepted_tokens',
    'draft tokens accepted by the target model (longest matching prefix); '
    'accepted/draft is the acceptance rate')
decode_spec_acceptance = _LazyMetric(
    'gauge', 'decode_spec_acceptance',
    'cumulative draft-token acceptance rate (accepted / proposed)')
decode_spec_verify_seconds = _LazyMetric(
    'histogram', 'decode_spec_verify_seconds',
    'wall seconds per batched (S, k) verify step — the verify-step split '
    'of decode time')
decode_spec_accept_len = _LazyMetric(
    'histogram', 'decode_spec_accept_len',
    'tokens emitted per slot per verify round (1 = all drafts rejected)',
    bounds=_ACCEPT_BOUNDS)
decode_tokens_sampled = _LazyMetric(
    'counter', 'decode_tokens_sampled',
    'tokens drawn through per-request sampling (temperature > 0) rather '
    'than greedy argmax')

decode_breaker_state = _LazyMetric(
    'gauge', 'decode_breaker_state',
    'decode-path circuit breaker state (0 closed / 1 half-open / 2 open)')
decode_breaker_trips = _LazyMetric(
    'counter', 'decode_breaker_trips',
    'decode-path breaker trips (consecutive-failure threshold or failed '
    'probe)')
decode_breaker_rejected = _LazyMetric(
    'counter', 'decode_breaker_rejected',
    'generation requests rejected fast with EngineUnhealthy while the '
    'decode breaker was open')
decode_breaker_probes = _LazyMetric(
    'counter', 'decode_breaker_probes',
    'half-open probe windows opened after the decode breaker cooldown')

DECODE_BREAKER_METRICS = {'state': decode_breaker_state,
                          'trips': decode_breaker_trips,
                          'rejected': decode_breaker_rejected,
                          'probes': decode_breaker_probes}


# -- serving tier (serving/tier/, docs/SERVING.md "Serving tier") ----------
# Same always-on discipline: the router/cache/handoff paths run per-request
# (ms-scale), and an operator scraping a router box must see these without
# PADDLE_TPU_TELEMETRY.

# radix prefix cache over the paged KV pool (tier/prefix_cache.py)
prefix_cache_hits = _LazyMetric(
    'counter', 'prefix_cache_hits',
    'admissions that matched >= 1 whole cached block of their prompt')
prefix_cache_misses = _LazyMetric(
    'counter', 'prefix_cache_misses',
    'admissions with no cached prefix (cold prompts)')
prefix_cache_tokens_saved = _LazyMetric(
    'counter', 'prefix_cache_tokens_saved',
    'prompt tokens served from cached KV blocks instead of prefill '
    'compute — the prefill-compute-saved signal')
prefix_cache_blocks_resident = _LazyMetric(
    'gauge', 'prefix_cache_blocks_resident',
    'KV blocks currently held resident by the prefix-cache trie')
prefix_cache_inserted_blocks = _LazyMetric(
    'counter', 'prefix_cache_inserted_blocks',
    'whole prompt blocks published into the trie')
prefix_cache_evicted_blocks = _LazyMetric(
    'counter', 'prefix_cache_evicted_blocks',
    'cached blocks evicted (LRU over refcount-idle leaves) under pool or '
    'cap pressure')
prefix_cache_evictions = _LazyMetric(
    'counter', 'prefix_cache_evictions',
    'blocks leaving HBM residency (spilled or dropped), labeled by cause: '
    'pressure = allocation ran dry, cap = publish hit '
    'PADDLE_TPU_PREFIX_CACHE_MAX_BLOCKS')

# quantized + tiered KV cache (PADDLE_TPU_KV_DTYPE storage dtype + the
# PADDLE_TPU_PREFIX_CACHE_HOST_MB host spill tier — docs/SERVING.md
# "Tiered KV cache")
kv_cache_dtype = _LazyMetric(
    'gauge', 'kv_cache_dtype',
    'KV pool storage dtype code (0 = f32, 1 = bf16, 2 = int8)')
kv_cache_bytes_in_hbm = _LazyMetric(
    'gauge', 'kv_cache_bytes_in_hbm',
    'resident KV pool bytes across allocated layers (payload arrays plus '
    'int8 row-scale arrays), sampled after pool writes')
kv_cache_bytes_spilled = _LazyMetric(
    'counter', 'kv_cache_bytes_spilled',
    'serialized KV payload bytes moved from HBM to the host spill tier')
kv_cache_spill_count = _LazyMetric(
    'counter', 'kv_cache_spill_count',
    'prefix-cache blocks spilled to host RAM instead of being dropped')
kv_cache_reinject_count = _LazyMetric(
    'counter', 'kv_cache_reinject_count',
    'spilled blocks re-scattered into HBM on a later radix hit')
kv_cache_reinject_seconds = _LazyMetric(
    'histogram', 'kv_cache_reinject_seconds',
    'wall seconds per host->HBM reinjection (deserialize + one block '
    'scatter per layer for the whole reinjected run)')

# multi-replica router (tier/router.py)
router_requests = _LazyMetric(
    'counter', 'router_requests', 'generation requests entering the router')
router_requests_completed = _LazyMetric(
    'counter', 'router_requests_completed',
    'routed requests that finished (done line / full reply)')
router_requests_failed = _LazyMetric(
    'counter', 'router_requests_failed',
    'routed requests that failed after streaming began (in-flight on a '
    'dying replica) or exhausted every replica')
router_requests_rerouted = _LazyMetric(
    'counter', 'router_requests_rerouted',
    'dispatch attempts moved to another replica before first byte '
    '(connection refused / 503 / replica died pre-stream) — the '
    'zero-drop failover counter')
router_no_replica = _LazyMetric(
    'counter', 'router_no_replica',
    'pick attempts that found no routable replica (all cold, draining, '
    'degraded, or dead)')
router_replicas_routable = _LazyMetric(
    'gauge', 'router_replicas_routable',
    'replicas currently healthy + warm + not draining')
router_replica_inflight = _LazyMetric(
    'gauge', 'router_replica_inflight',
    'router-side in-flight requests per replica (label replica)')
router_dispatch_seconds = _LazyMetric(
    'histogram', 'router_dispatch_seconds',
    'submit -> replica response headers per dispatch attempt')
router_health_polls = _LazyMetric(
    'counter', 'router_health_polls', 'replica /healthz polls issued')
router_probes = _LazyMetric(
    'counter', 'router_probes',
    'requests routed to a half-open (probing) replica to re-admit it')
router_rolling_restarts = _LazyMetric(
    'counter', 'router_rolling_restarts',
    'replicas restarted behind a drain by rolling_restart()')

# elastic autoscaler (elastic/autoscaler.py; docs/SERVING.md "Autoscaler")
autoscale_decisions = _LazyMetric(
    'counter', 'autoscale_decisions',
    'autoscaler decisions taken (labels action=up|down, trigger='
    'queue_depth|ttft_p99|occupancy|min_replicas)')
autoscale_replicas = _LazyMetric(
    'gauge', 'autoscale_replicas',
    'replicas under autoscaler management (including cold pending ones, '
    'excluding draining-for-retirement ones)')
autoscale_replicas_routable = _LazyMetric(
    'gauge', 'autoscale_replicas_routable',
    'managed replicas currently healthy + warm + not draining')
autoscale_time_to_routable_seconds = _LazyMetric(
    'histogram', 'autoscale_time_to_routable_seconds',
    'scale-up launch -> replica routable (spawn + warmup gate + fast '
    'initial health poll)')
autoscale_drain_seconds = _LazyMetric(
    'histogram', 'autoscale_drain_seconds',
    'scale-down drain start -> replica idle (router in-flight 0 and '
    'replica queue empty) and retired')

# fleet-wide observability (PR 17, docs/OBSERVABILITY.md "Fleet-wide")
decode_ttft_seconds = _LazyMetric(
    'histogram', 'decode_ttft_seconds',
    'submit -> first emitted token per generation (time-to-first-token)')
router_scrape_failures = _LazyMetric(
    'counter', 'router_scrape_failures',
    'replica /metrics scrapes that failed or timed out during a '
    '/metrics/fleet aggregation (label replica)')
router_fleet_scrapes = _LazyMetric(
    'counter', 'router_fleet_scrapes',
    '/metrics/fleet aggregations served')
trace_requests_sampled = _LazyMetric(
    'counter', 'trace_requests_sampled',
    'requests that carried (router) or received (replica) a sampled '
    'trace context')
trace_spans_recorded = _LazyMetric(
    'counter', 'trace_spans_recorded',
    'distributed-trace spans recorded by this process')
trace_clock_offset_seconds = _LazyMetric(
    'gauge', 'trace_clock_offset_seconds',
    'estimated replica-minus-router wall-clock offset from the health '
    'handshake (label replica) — the trace-merge alignment input')

# disaggregated prefill/decode (tier/disagg.py)
disagg_handoffs = _LazyMetric(
    'counter', 'disagg_handoffs',
    'prefill->decode KV handoffs completed')
disagg_handoff_failures = _LazyMetric(
    'counter', 'disagg_handoff_failures',
    'handoffs that failed (prefill error); the request fails typed, the '
    'decode loop keeps stepping')
disagg_handoff_seconds = _LazyMetric(
    'histogram', 'disagg_handoff_seconds',
    'admission -> KV blocks injected into the decode pool, per handoff')
disagg_kv_bytes = _LazyMetric(
    'counter', 'disagg_kv_bytes',
    'KV payload bytes shipped from prefill to decode replicas')
disagg_pending = _LazyMetric(
    'gauge', 'disagg_pending',
    'admitted requests waiting on a prefill handoff right now')
