"""Circuit breaker: stop feeding a broken engine one batch at a time.

Failure isolation (batcher.py) keeps the worker alive through an engine
error, but with a PERSISTENTLY broken engine (driver wedged after a device
reset, model buffers poisoned, OOM loop) isolation alone is the worst of
both worlds: every queued request waits out its full deadline only to fail,
``/healthz`` still answers "ok", and the load balancer keeps routing
traffic in. The breaker is the standard three-state remedy:

- **closed** — healthy. Engine failures increment a consecutive-failure
  count; any success resets it.
- **open** — tripped after `failure_threshold` CONSECUTIVE engine-failure
  batches. Queued requests are failed immediately and new submissions are
  rejected in O(µs) with the typed :class:`EngineUnhealthy` (HTTP 503) —
  clients fail over instead of waiting out deadlines, and ``/healthz``
  reports ``degraded`` so the balancer stops routing here.
- **half-open** — after `reset_after_s` the next submission is admitted as
  a **probe**: its batch actually runs. Success closes the breaker (full
  service resumes, no restart needed); failure re-opens it and restarts
  the cooldown.

State + trip counts are exported through the always-on serving metric
handles the caller passes in (`serving_breaker_*` / `decode_breaker_*`,
docs/OBSERVABILITY.md). Thread-safe; `allow()` is called on submitter
threads, the record hooks on the single worker thread.
"""
from __future__ import annotations

import logging
import os
import threading
import time

from ..log_helper import get_logger

__all__ = ['CircuitBreaker', 'DEFAULT_FAILURE_THRESHOLD',
           'DEFAULT_RESET_AFTER_S']

_logger = get_logger(
    __name__, logging.INFO,
    fmt='%(asctime)s-%(levelname)s: [serving] %(message)s')

DEFAULT_FAILURE_THRESHOLD = int(
    os.environ.get('PADDLE_TPU_SERVING_BREAKER_FAILURES', '5'))
DEFAULT_RESET_AFTER_S = float(
    os.environ.get('PADDLE_TPU_SERVING_BREAKER_RESET_S', '5'))

#: numeric encoding of the state gauge (docs/OBSERVABILITY.md)
STATE_CODES = {'closed': 0, 'half_open': 1, 'open': 2}


class CircuitBreaker:
    """See module docstring. `metrics` is a dict of always-on lazy metric
    handles: ``state`` (gauge), ``trips`` / ``rejected`` / ``probes``
    (counters) — passed in so the predict and decode paths export under
    their own prefixes."""

    def __init__(self, failure_threshold=None, reset_after_s=None,
                 metrics=None, name='engine'):
        self.failure_threshold = int(
            failure_threshold if failure_threshold is not None
            else DEFAULT_FAILURE_THRESHOLD)
        self.reset_after_s = float(
            reset_after_s if reset_after_s is not None
            else DEFAULT_RESET_AFTER_S)
        self.name = name
        self._m = metrics or {}
        self._lock = threading.Lock()
        self._state = 'closed'
        self._consecutive_failures = 0
        self._opened_at = None
        self.trips = 0

    # ------------------------------------------------------------------
    @property
    def state(self):
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    @property
    def consecutive_failures(self):
        return self._consecutive_failures

    def _set_state_locked(self, state):
        self._state = state
        m = self._m.get('state')
        if m is not None:
            m.set(STATE_CODES[state])

    def _maybe_half_open_locked(self):
        if (self._state == 'open'
                and time.monotonic() - self._opened_at >= self.reset_after_s):
            self._set_state_locked('half_open')
            m = self._m.get('probes')
            if m is not None:
                m.inc()
            _logger.info('%s breaker half-open: admitting a probe batch',
                         self.name)

    # ------------------------------------------------------------------
    def allow(self):
        """Submission gate: True = admit. False only while OPEN (and still
        cooling down) — the caller rejects with EngineUnhealthy without
        touching the queue, which is what makes rejection O(µs)."""
        with self._lock:
            if self._state == 'closed':
                return True
            self._maybe_half_open_locked()
            if self._state == 'half_open':
                return True
            m = self._m.get('rejected')
            if m is not None:
                m.inc()
            return False

    def record_success(self):
        """One engine batch answered. Closes a half-open breaker."""
        with self._lock:
            self._consecutive_failures = 0
            if self._state != 'closed':
                self._set_state_locked('closed')
                _logger.info('%s breaker closed: probe succeeded, service '
                             'restored', self.name)

    def record_failure(self):
        """One engine batch failed. → True exactly when this failure TRIPS
        the breaker (closed→open past the threshold, or a failed half-open
        probe) — the caller then fails its queued work fast."""
        with self._lock:
            self._consecutive_failures += 1
            if self._state == 'half_open':
                self._trip_locked('probe batch failed')
                return True
            if (self._state == 'closed'
                    and self._consecutive_failures >= self.failure_threshold):
                self._trip_locked(
                    f'{self._consecutive_failures} consecutive '
                    f'engine-failure batches')
                return True
            return False

    def _trip_locked(self, why):
        self._set_state_locked('open')
        self._opened_at = time.monotonic()
        self.trips += 1
        m = self._m.get('trips')
        if m is not None:
            m.inc()
        _logger.error(
            '%s breaker OPEN (%s): failing queued requests, rejecting new '
            'ones for %.1fs, then probing', self.name, why,
            self.reset_after_s)
