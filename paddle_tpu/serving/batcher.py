"""Dynamic micro-batcher: coalesce concurrent requests into bucketed batches.

The throughput problem with per-request TPU dispatch is fixed cost: one
device call costs roughly the same whether it carries 1 row or 16, so a
server that dispatches per request wastes almost the whole machine
(PERF.md §11 measures ~15× at bucket 16 on CPU). The batcher turns N
concurrent small requests into one bucketed device call:

    submit() ─ validate ─▶ bounded queue ─▶ worker thread ─▶ engine.run_batch
                 │              │               │ coalesce ≤ max_batch rows
          InvalidRequest    Overloaded          │ or wait ≤ batch_timeout_ms
          (never enqueued)  (queue full)        ▼
                                          per-request futures

Robustness invariants, each tested in tests/framework/test_serving.py:

- **validation before enqueue**: a malformed request raises at submit() and
  never reaches a batch — co-batched requests cannot be poisoned;
- **bounded queue**: a full queue raises the typed ``Overloaded`` instead of
  growing latency without bound (backpressure, not buffering);
- **per-request deadlines**: a request whose deadline expires while queued
  is dropped (``DeadlineExceeded``) before it wastes device time;
- **failure isolation**: an engine error fails exactly the requests in that
  batch — the worker survives and keeps serving;
- **circuit breaker** (breaker.py): `breaker_failures` CONSECUTIVE
  engine-failure batches trip the breaker — queued requests fail
  immediately and new ones are rejected in O(µs) with the typed
  ``EngineUnhealthy`` instead of waiting out their deadlines against a
  broken engine; after the cooldown a half-open probe batch re-admits
  traffic once the engine answers again (no restart);
- **graceful shutdown**: ``close(drain=True)`` stops admission, drains every
  queued request, then joins the worker. ``drain=False`` fails the queue
  fast with ``EngineClosed``.
"""
from __future__ import annotations

import collections
import os
import threading
import time

import numpy as np

from . import metrics as _m
from .breaker import CircuitBreaker
from .errors import (DeadlineExceeded, EngineClosed, EngineUnhealthy,
                     Overloaded, ServingError)

__all__ = ['MicroBatcher', 'PredictionFuture', 'DEFAULT_BATCH_TIMEOUT_MS',
           'DEFAULT_QUEUE_DEPTH']

DEFAULT_BATCH_TIMEOUT_MS = float(
    os.environ.get('PADDLE_TPU_SERVING_TIMEOUT_MS', '2'))
DEFAULT_QUEUE_DEPTH = int(
    os.environ.get('PADDLE_TPU_SERVING_QUEUE_DEPTH', '128'))


class PredictionFuture:
    """Completion handle for one submitted request."""

    def __init__(self):
        self._done = threading.Event()
        self._value = None
        self._exc = None
        self._callbacks = []
        self._cb_lock = threading.Lock()

    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block for the outcome. Raises the request's failure
        (DeadlineExceeded / EngineClosed / ServingError) or TimeoutError if
        the outcome itself does not arrive within ``timeout`` seconds."""
        if not self._done.wait(timeout):
            raise TimeoutError('prediction not completed in time')
        if self._exc is not None:
            raise self._exc
        return self._value

    def add_done_callback(self, fn):
        """``fn(future)`` runs when the outcome lands — on the completing
        (batcher worker) thread, or immediately on the caller if already
        done. Open-loop load generators use this to timestamp completions
        without a waiter thread per in-flight request (tools/
        bench_serving.py's Poisson section). Keep callbacks cheap: they
        run on the serving hot path. Callback exceptions are swallowed."""
        with self._cb_lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        self._run_callback(fn)

    def _run_callback(self, fn):
        try:
            fn(self)
        except Exception:
            pass                     # a bench/observer bug must not poison
                                     # the batch that completed this future

    # -- batcher-side completion (exactly once) ---------------------------
    def _finish(self):
        with self._cb_lock:
            self._done.set()
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            self._run_callback(fn)

    def _set_result(self, value):
        self._value = value
        self._finish()

    def _set_exception(self, exc):
        self._exc = exc
        self._finish()


class _Request:
    __slots__ = ('feed', 'nrows', 'future', 'enqueued_at', 'deadline')

    def __init__(self, feed, nrows, deadline):
        self.feed = feed
        self.nrows = nrows
        self.future = PredictionFuture()
        self.enqueued_at = time.monotonic()
        self.deadline = deadline            # absolute monotonic, or None

    def expired(self, now):
        return self.deadline is not None and now > self.deadline


class MicroBatcher:
    """Bounded-queue micro-batcher in front of an :class:`InferenceEngine`
    (or anything duck-typed with validate / run_batch / max_batch_size).

    - ``max_batch_size``: row budget per device call (default: engine's).
    - ``batch_timeout_ms``: how long a non-full batch waits for company.
      0 disables coalescing-by-time (batch = whatever is already queued).
    - ``queue_depth``: admission bound, in requests. Full → ``Overloaded``.
    - ``default_timeout_ms``: per-request deadline applied when submit()
      gets none. None = requests wait forever.
    """

    def __init__(self, engine, max_batch_size=None,
                 batch_timeout_ms=DEFAULT_BATCH_TIMEOUT_MS,
                 queue_depth=DEFAULT_QUEUE_DEPTH, default_timeout_ms=None,
                 breaker_failures=None, breaker_reset_s=None, start=True):
        self.engine = engine
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_failures, reset_after_s=breaker_reset_s,
            metrics=_m.PREDICT_BREAKER_METRICS, name='predict engine')
        engine_max = int(getattr(engine, 'max_batch_size', 0) or 0)
        self.max_batch_size = int(max_batch_size or engine_max or 16)
        if engine_max:
            # never coalesce more rows than the engine's top bucket holds —
            # such a batch could only fail wholesale at bucket_for()
            self.max_batch_size = min(self.max_batch_size, engine_max)
        self.batch_timeout = float(batch_timeout_ms) / 1e3
        self.queue_depth = int(queue_depth)
        self.default_timeout_ms = default_timeout_ms
        self._queue = collections.deque()
        self._carry = None                   # dequeued but didn't fit
        self._cv = threading.Condition()
        self._closing = False
        self._closed = False
        self._drain = True
        self._worker = threading.Thread(target=self._worker_loop,
                                        name='paddle-tpu-serving-batcher',
                                        daemon=True)
        if start:
            self._worker.start()

    # -- client side -------------------------------------------------------
    def submit(self, inputs, timeout_ms=None):
        """Validate and enqueue one request; returns a
        :class:`PredictionFuture`. Raises InvalidRequest (bad request, not
        enqueued), Overloaded (queue full, not enqueued), EngineUnhealthy
        (circuit breaker open — reject BEFORE validation so clients fail
        over in O(µs) regardless of payload size), or EngineClosed
        (shutdown begun)."""
        if not self.breaker.allow():
            raise EngineUnhealthy('predict engine',
                                  self.breaker.consecutive_failures)
        try:
            feed, nrows = self.engine.validate(inputs)
        except Exception:
            _m.requests_rejected_invalid.inc()
            raise
        if timeout_ms is None:
            timeout_ms = self.default_timeout_ms
        deadline = None if timeout_ms is None \
            else time.monotonic() + float(timeout_ms) / 1e3
        req = _Request(feed, nrows, deadline)
        with self._cv:
            if self._closing:
                raise EngineClosed('serving engine is shutting down')
            if len(self._queue) >= self.queue_depth:
                _m.requests_rejected_overload.inc()
                raise Overloaded(len(self._queue))
            self._queue.append(req)
            _m.requests_accepted.inc()
            _m.queue_depth.set(len(self._queue))
            self._cv.notify()
        return req.future

    def predict(self, inputs, timeout_ms=None):
        """Synchronous convenience: submit + wait. The wait is bounded by the
        request deadline (plus compute slack) when one is set."""
        fut = self.submit(inputs, timeout_ms)
        ms = timeout_ms if timeout_ms is not None else self.default_timeout_ms
        wait = None if ms is None else float(ms) / 1e3 + 60.0
        return fut.result(wait)

    def pending(self):
        with self._cv:
            return len(self._queue) + (1 if self._carry is not None else 0)

    # -- worker side -------------------------------------------------------
    def _take_first(self):
        """Block for the request that opens the next batch; None = shut
        down. The carry-over (dequeued last round but over the row budget)
        goes first — FIFO is preserved."""
        with self._cv:
            while True:
                if self._carry is not None:
                    req, self._carry = self._carry, None
                    return req
                if self._queue:
                    req = self._queue.popleft()
                    _m.queue_depth.set(len(self._queue))
                    return req
                if self._closing:
                    return None
                self._cv.wait(timeout=0.1)

    def _fill_batch(self, first):
        """Coalesce: after ``first``, keep taking requests until the row
        budget fills or the batch window closes."""
        batch, rows = [first], first.nrows
        window_ends = time.monotonic() + self.batch_timeout
        while rows < self.max_batch_size:
            with self._cv:
                if not self._queue:
                    if self._closing:
                        break               # draining: never wait for more
                    remaining = window_ends - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
                    if not self._queue:
                        continue
                if self._queue[0].nrows + rows > self.max_batch_size:
                    # would overflow: hold it as the opener of the next batch
                    self._carry = self._queue.popleft()
                    _m.queue_depth.set(len(self._queue))
                    break
                req = self._queue.popleft()
                _m.queue_depth.set(len(self._queue))
            batch.append(req)
            rows += req.nrows
        return batch

    def _execute(self, batch):
        now = time.monotonic()
        live = []
        for req in batch:
            if req.expired(now):
                _m.requests_deadline_missed.inc()
                req.future._set_exception(DeadlineExceeded(
                    'deadline expired after '
                    f'{now - req.enqueued_at:.3f}s in queue'))
            else:
                live.append(req)
        if not live:
            return
        for req in live:
            _m.queue_wait_seconds.observe(now - req.enqueued_at)
        nrows = sum(r.nrows for r in live)
        feed = {name: np.concatenate([r.feed[name] for r in live])
                for name in live[0].feed}
        try:
            outs = self.engine.run_batch(feed, nrows)
        except Exception as e:
            # engine failure poisons exactly this batch; the worker survives
            _m.requests_failed.inc(len(live))
            err = e if isinstance(e, ServingError) else ServingError(
                f'inference failed: {type(e).__name__}: {e}')
            for req in live:
                req.future._set_exception(err)
            if self.breaker.record_failure():
                # just tripped: everything still queued would only wait out
                # its deadline against a broken engine — fail it all NOW
                self._fail_queued(EngineUnhealthy(
                    'predict engine', self.breaker.consecutive_failures))
            return
        self.breaker.record_success()
        off = 0
        for req in live:
            req.future._set_result([o[off:off + req.nrows] for o in outs])
            off += req.nrows
        _m.requests_completed.inc(len(live))

    def _fail_queued(self, exc):
        """Fail every queued (and carried-over) request with `exc`."""
        with self._cv:
            failed = 0
            if self._carry is not None:
                self._carry.future._set_exception(exc)
                self._carry = None
                failed += 1
            while self._queue:
                self._queue.popleft().future._set_exception(exc)
                failed += 1
            _m.queue_depth.set(0)
        if failed:
            _m.requests_failed.inc(failed)

    def _worker_loop(self):
        while True:
            first = self._take_first()
            if first is None:
                break
            self._execute(self._fill_batch(first))

    # -- lifecycle ---------------------------------------------------------
    def close(self, drain=True, timeout=None):
        """Stop admission, then either drain queued requests (default) or
        fail them fast with EngineClosed. Idempotent; joins the worker.
        A later ``close(drain=False)`` while a drain is still running
        ESCALATES it: remaining queued requests fail fast (the SIGTERM
        drain-timeout path in server.py)."""
        with self._cv:
            self._closing = True
            if not drain:
                while self._queue:
                    req = self._queue.popleft()
                    req.future._set_exception(
                        EngineClosed('serving engine shut down before '
                                     'this request ran'))
                _m.queue_depth.set(0)
            self._cv.notify_all()
        if self._worker.is_alive():
            self._worker.join(timeout)
        self._closed = True

    @property
    def closed(self):
        return self._closed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
