"""Weight-decay regularizers (ref: python/paddle/fluid/regularizer.py)."""
from __future__ import annotations


class WeightDecayRegularizer:
    def append_regularization_op(self, param, grad):
        """Return new grad Variable = grad + penalty'(param) (static mode)."""
        raise NotImplementedError

    def apply(self, p, g):
        """Functional form for dygraph/jit paths."""
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self.coeff = float(regularization_coeff)

    def append_regularization_op(self, param, grad):
        from .layers.common import apply_op_layer
        decay = apply_op_layer('scale', {'x': param}, {'scale': self.coeff})
        return apply_op_layer('elementwise_add', {'x': grad, 'y': decay})

    def apply(self, p, g):
        return g + self.coeff * p


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self.coeff = float(regularization_coeff)

    def append_regularization_op(self, param, grad):
        from .layers.common import apply_op_layer
        s = apply_op_layer('sign', {'x': param})
        decay = apply_op_layer('scale', {'x': s}, {'scale': self.coeff})
        return apply_op_layer('elementwise_add', {'x': grad, 'y': decay})

    def apply(self, p, g):
        import jax.numpy as jnp
        return g + self.coeff * jnp.sign(p)


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer


def append_regularization_ops(params_grads, regularization=None):
    """ref: regularizer.py:append_regularization_ops — param-level regularizer
    wins over the optimizer-level one."""
    out = []
    for p, g in params_grads:
        reg = getattr(p, 'regularizer', None) or regularization
        if reg is not None and getattr(p, 'trainable', True):
            g = reg.append_regularization_op(p, g)
        out.append((p, g))
    return out
