"""Program-level IR pass pipeline (see pass_base.py for the design notes).

Entry points:

- :func:`apply_pipeline` — what ``Executor._run_impl`` calls on a
  program+shape compile-cache miss: builds the pipeline for the run's
  BuildStrategy, applies it to a clone, returns the optimized Program.
- :func:`pipeline_signature` — hashable description of which passes WOULD
  run; part of the executor's compile-cache key so flipping a fuse knob
  or ``PADDLE_TPU_PASSES`` re-lowers instead of reusing a stale step.

Environment: ``PADDLE_TPU_PASSES`` — unset/``1`` = default pipeline
(constant_fold + dce always; fuse passes per BuildStrategy flags),
``0``/empty = pipeline off entirely, or a comma-separated pass list
(e.g. ``dce,fuse_all_optimizer_ops``) = exactly those passes, flags
ignored.

Post-condition (``PADDLE_TPU_VERIFY`` ∈ {``passes``, ``full``},
docs/ANALYSIS.md): after every pass that changes the program, the static
verifier (``paddle_tpu/analysis/``) re-checks it at the pass boundary —
a pass emitting an inconsistent program (dangling reads, mixed-dtype
buckets, lost ``_rng_salt`` stamps, …) raises
``ProgramVerificationError`` naming the pass, instead of surfacing as an
opaque trace error three layers later. See ``pass_base.PassManager``.
"""
from __future__ import annotations

import os

from .pass_base import (Pass, PassContext, PassManager, all_passes,  # noqa: F401
                        get_pass, register_pass, stamp_rng_salts)
from . import (constant_fold, dce, fuse_act,  # noqa: F401  (registration)
               fuse_optimizer, bucket_allreduce, auto_remat)

__all__ = ['Pass', 'PassContext', 'PassManager', 'register_pass',
           'get_pass', 'all_passes', 'apply_pipeline', 'build_pipeline',
           'pipeline_signature', 'passes_env']

# always-safe passes, on by default; the fuse passes additionally gate on
# their BuildStrategy flag (or, for bucket_allreduce, the fleet
# DistributedStrategy stamp), and auto_remat on PADDLE_TPU_HBM_BUDGET_MB,
# inside apply_impl
_DEFAULT_PASSES = ('constant_fold', 'fuse_elewise_add_act',
                   'bucket_allreduce', 'fuse_all_optimizer_ops',
                   'auto_remat', 'dce')


def passes_env():
    return os.environ.get('PADDLE_TPU_PASSES', '1')


def _selected_names():
    env = passes_env().strip()
    if env in ('0', ''):
        return ()
    if env == '1':
        return _DEFAULT_PASSES
    return tuple(n.strip() for n in env.split(',') if n.strip())


def build_pipeline():
    """PassManager for the current environment selection (may be empty)."""
    return PassManager([get_pass(n) for n in _selected_names()])


_FLAG_GATED = {'fuse_elewise_add_act': 'fuse_elewise_add_act_ops',
               'fuse_all_optimizer_ops': 'fuse_all_optimizer_ops',
               'bucket_allreduce': 'fuse_all_reduce_ops'}


def pipeline_signature(build_strategy=None):
    """Hashable 'which rewrites apply' tuple for the compile-cache key."""
    names = _selected_names()
    if not names:
        return ()
    env = passes_env().strip()
    if env == '1':
        # flag-gated passes only count when their flag is live (the fleet
        # program-stamp path for bucket_allreduce is per-program and thus
        # already covered by the cache key's program id+version)
        bs = build_strategy
        names = tuple(
            n for n in names
            if n not in _FLAG_GATED
            or (bs is not None and getattr(bs, _FLAG_GATED[n], False)))
    if 'bucket_allreduce' in names:
        # the cap changes the rewrite, so it must re-lower on change;
        # '=auto' resolves per program (whose id/version is already in
        # the executor's cache key), so the tag alone suffices
        cap = ('auto' if bucket_allreduce.bucket_cap_is_auto()
               else bucket_allreduce.bucket_cap_bytes())
        names = tuple(f'bucket_allreduce@{cap}'
                      if n == 'bucket_allreduce' else n for n in names)
    if 'auto_remat' in names:
        # env-gated like the flag-gated fuses: absent budget → the pass
        # cannot change anything; present budget is part of the rewrite
        budget = auto_remat.hbm_budget_bytes()
        names = tuple(f'auto_remat@{budget}' if n == 'auto_remat' else n
                      for n in names) if budget is not None else \
            tuple(n for n in names if n != 'auto_remat')
    return names


def apply_pipeline(program, fetch_names=(), feed_names=(),
                   build_strategy=None, feed_shapes=None):
    """Optimized CLONE of `program` (or `program` itself when the pipeline
    is disabled), plus the PassContext carrying per-pass stats.
    `feed_shapes` (name → concrete shape) lets shape-sensitive passes —
    auto_remat's memory plan — price dynamic batch dims exactly; the
    executor passes the run's real feed signature."""
    mgr = build_pipeline()
    ctx = PassContext(fetch_names=fetch_names, feed_names=feed_names,
                      build_strategy=build_strategy,
                      feed_shapes=feed_shapes)
    if not mgr.passes:
        return program, ctx
    opt, ctx = mgr.apply(program, ctx)
    return opt, ctx
