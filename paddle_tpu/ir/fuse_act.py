"""``fuse_elewise_add_act``: elementwise_add + activation → one fused op.

Parity target: the reference's fuse_elewise_add_act_pass.cc, gated by the
same ``BuildStrategy.fuse_elewise_add_act_ops`` knob. The win on TPU is
front-end, not kernel: XLA fuses add+act on its own, but the Python
tracer pays two ``_OpRunner`` dispatches, two env writes, and two jaxpr
bookkeeping rounds per pair — in an fc/conv-heavy program the (bias-add,
act) pair is ~2 of every 5 forward ops.

Safety conditions for a pair (add at i, act at j > i):
- the intermediate is consumed ONLY by the act op (sub-block reads
  counted), is not fetched, not persistable, and has no other writer;
- nothing between i and j rewrites the add's inputs (the fused op reads
  them at position j).

Skipped entirely under AMP: the rewrite would change which ops the
white/black dtype lists match (``executor._amp_cast_args`` keys on
``op.type``).
"""
from __future__ import annotations

from .pass_base import Pass, register_pass
from .dce import _op_read_names

# activation op types the fused kernel implements (ops/fused_ops.py)
FUSABLE_ACTS = ('relu', 'sigmoid', 'tanh')


@register_pass
class FuseElewiseAddActPass(Pass):
    name = 'fuse_elewise_add_act'
    order = 200

    def enabled(self, ctx):
        bs = ctx.build_strategy
        return bs is not None and getattr(bs, 'fuse_elewise_add_act_ops',
                                          False)

    def apply_impl(self, program, ctx):
        if not self.enabled(ctx) or getattr(program, '_amp_config', None):
            return False
        blk = program.global_block()
        ops = blk.ops
        fetch = set(ctx.fetch_names)
        persist = {v.name for v in program.list_vars() if v.persistable}
        # names the lowering resolves through marker ATTRS (not op inputs):
        # remat checkpoints and pipeline cut vars must keep their producers
        protected = set()
        for op in ops:
            protected.update(op.attrs.get('checkpoints') or [])
            pipe = op.attrs.get('pipeline')
            if isinstance(pipe, dict):
                protected.update(pipe.get('cut_vars') or [])

        readers = {}                     # var → [op index]
        writers = {}
        for idx, op in enumerate(ops):
            for n in _op_read_names(op):
                readers.setdefault(n, []).append(idx)
            for n in op.output_names():
                writers.setdefault(n, []).append(idx)

        from ..framework import Operator
        from .pass_base import RNG_SALT_ATTR
        replaced = {}                    # act index → fused Operator
        dead = set()                     # add indices to drop
        for i, add in enumerate(ops):
            if add.type != 'elementwise_add' or i in dead:
                continue
            mid = add.outputs['Out'][0]
            if (mid in fetch or mid in persist or mid in protected
                    or writers.get(mid, []) != [i]):
                continue
            cons = readers.get(mid, [])
            if len(cons) != 1:
                continue
            j = cons[0]
            act = ops[j]
            if (j <= i or j in replaced or act.type not in FUSABLE_ACTS
                    or act.inputs.get('x', [None])[0] != mid):
                continue
            x, y = add.inputs['x'][0], add.inputs['y'][0]
            if any(k for n in (x, y) for k in writers.get(n, [])
                   if i < k < j):
                continue
            attrs = {'functor': act.type,
                     'axis': add.attrs.get('axis', -1)}
            if RNG_SALT_ATTR in act.attrs:
                attrs[RNG_SALT_ATTR] = act.attrs[RNG_SALT_ATTR]
            fused = Operator(
                blk, 'fused_elemwise_add_activation',
                inputs={'x': x, 'y': y},
                outputs={'Out': list(act.outputs['Out'])}, attrs=attrs)
            fused._site = add._site    # diagnostics point at the add's origin
            replaced[j] = fused
            dead.add(i)
        if not replaced:
            return False
        blk.ops = [replaced.get(idx, op) for idx, op in enumerate(ops)
                   if idx not in dead]
        ctx.record(self.name, fused_pairs=len(replaced))
        return True
