"""Constant folding of ``fill_constant`` → ``scale`` / ``cast`` chains.

``fill_constant`` already materializes a trace-time numpy constant (see
ops/tensor_ops.py — concreteness is load-bearing for TensorArray indices
and loop counters). A ``scale`` or ``cast`` of a uniform constant is
itself a uniform constant, so the consumer is rewritten INTO an equivalent
``fill_constant`` — same op type, same concreteness guarantee, no new
runtime representation — and the original producer is left for DCE to
sweep once its last reader is folded away.

Folding uses forward current-value dataflow over the straight-line global
block: a later non-constant write to the same name invalidates the known
constant, so multi-writer vars (grad-merge accumulators being zeroed,
reassigned counters) fold only where the constant value is actually the
live one. The arithmetic runs in numpy at the var's own dtype — exactly
what the scale/cast kernels would have computed elementwise — so folded
and unfolded programs are bit-identical.
"""
from __future__ import annotations

import numpy as np

from ..framework import Operator
from .pass_base import RNG_SALT_ATTR, Pass, register_pass


def _np_dtype(dtype_str):
    if dtype_str in ('bfloat16',):
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(dtype_str)


@register_pass
class ConstantFoldingPass(Pass):
    name = 'constant_fold'
    order = 100          # first: fusion passes then see folded constants

    def apply_impl(self, program, ctx):
        blk = program.global_block()
        consts = {}      # var name → (value_scalar, dtype_str, shape) LIVE now
        folded = 0
        for i, op in enumerate(blk.ops):
            new = self._fold_op(op, consts)
            if new is not None:
                blk.ops[i] = new
                op = new
                folded += 1
            if op.type == 'fill_constant':
                a = op.attrs
                consts[op.outputs['Out'][0]] = (
                    a['value'], a.get('dtype', 'float32'), tuple(a['shape']))
            else:
                for out in op.output_names():
                    consts.pop(out, None)
        ctx.record(self.name, folded_ops=folded)
        return bool(folded)

    @staticmethod
    def _fold_op(op, consts):
        """scale/cast over a live constant → equivalent fill_constant op."""
        if op.type not in ('scale', 'cast'):
            return None
        src = op.inputs.get('x', [None])[0]
        if src not in consts:
            return None
        value, dtype_str, shape = consts[src]
        dt = _np_dtype(dtype_str)
        if op.type == 'scale':
            # mirror the kernel bit-for-bit: s/b cast to x.dtype first
            x = np.asarray(value, dt)
            s = np.asarray(op.attrs.get('scale', 1.0), dt)
            b = np.asarray(op.attrs.get('bias', 0.0), dt)
            out_val = (x * s + b if op.attrs.get('bias_after_scale', True)
                       else (x + b) * s)
            out_dtype = dtype_str
        else:                          # cast
            out_dtype = op.attrs['dtype']
            out_val = np.asarray(value, dt).astype(_np_dtype(out_dtype))
        attrs = {'shape': list(shape), 'value': out_val[()],
                 'dtype': out_dtype}
        if RNG_SALT_ATTR in op.attrs:
            attrs[RNG_SALT_ATTR] = op.attrs[RNG_SALT_ATTR]
        new = Operator(op.block, 'fill_constant', inputs={},
                       outputs={'Out': list(op.outputs['Out'])}, attrs=attrs)
        new._site = op._site       # diagnostics keep the folded op's origin
        return new
