"""``auto_remat``: budget-driven automatic rematerialization.

``RecomputeOptimizer`` has always had the *mechanism* — checkpoint names
on the backward marker lower to ``jax.checkpoint`` segments
(``executor._remat_segments``) — but the checkpoints were hand-picked.
This pass makes the choice automatic: when ``PADDLE_TPU_HBM_BUDGET_MB``
is set and the memory planner (``analysis/plan.py``) predicts the
program's peak HBM exceeds it, the plan's greedy selector picks
activation-segment boundaries (narrow live-set waists — low
FLOPs-per-byte-saved, since recompute costs one extra forward pass no
matter how many boundaries are chosen) and writes them into the marker's
``checkpoints`` attr. The lowering then remats exactly as if the user
had called ``RecomputeOptimizer._set_checkpoints`` with the same names —
bitwise-identical numerics by construction (asserted in
tests/framework/test_memory_plan.py).

Manual checkpoints always win: a marker that already carries a
checkpoint list is never overridden. Programs without a backward marker,
already under budget, or with no helpful boundary are left untouched
(the shortfall is reported once through log_helper, not raised — an
optimistic budget must not kill training that might still fit).

The budget is part of ``ir.pipeline_signature`` so changing it re-lowers
instead of reusing a stale step. Zero per-step cost: the pass (and the
plan it runs) executes once per program+shape compile-cache miss.
"""
from __future__ import annotations

import logging
import os

from .. import observability as _obs
from ..framework import BACKWARD_OP_TYPE
from ..log_helper import get_logger
from .pass_base import Pass, register_pass

ENV_HBM_BUDGET = 'PADDLE_TPU_HBM_BUDGET_MB'

_logger = get_logger(__name__, logging.WARNING)
_warned_shortfall = set()


def hbm_budget_bytes():
    """The simulated-HBM budget in bytes, or None when unset. Strict
    parse: non-numeric / non-positive values raise listing the contract
    (same knob discipline as every other PADDLE_TPU_* env)."""
    raw = os.environ.get(ENV_HBM_BUDGET)
    if raw is None or raw == '':
        return None
    try:
        mb = float(raw)
    except ValueError:
        raise ValueError(
            f'{ENV_HBM_BUDGET}: expected a number of MiB (e.g. 2048), '
            f'got {raw!r}')
    if mb <= 0:
        raise ValueError(f'{ENV_HBM_BUDGET}: must be > 0, got {raw!r}')
    return int(mb * (1 << 20))


@register_pass
class AutoRematPass(Pass):
    name = 'auto_remat'
    # after the fuse passes (the plan must price the ops that will
    # actually lower), before DCE's final sweep
    order = 350

    def apply_impl(self, program, ctx):
        budget = hbm_budget_bytes()
        if budget is None:
            return False
        blk = program.global_block()
        marker = next((op for op in blk.ops
                       if op.type == BACKWARD_OP_TYPE), None)
        if marker is None:
            return False
        if marker.attrs.get('checkpoints'):
            return False          # manual RecomputeOptimizer wins
        from ..analysis.plan import select_checkpoints
        feed_shapes = getattr(ctx, 'feed_shapes', None)
        names, new_peak = select_checkpoints(
            program, budget, fetch_names=ctx.fetch_names,
            feed_names=ctx.feed_names, feed_shapes=feed_shapes)
        if not names:
            if new_peak > budget and program._id not in _warned_shortfall:
                _warned_shortfall.add(program._id)
                _logger.warning(
                    'auto_remat: no checkpoint boundary brings predicted '
                    'peak %.1f MiB under %s=%.1f MiB; leaving the program '
                    'unrematerialized', new_peak / 2**20,
                    ENV_HBM_BUDGET, budget / 2**20)
            return False
        marker.attrs['checkpoints'] = list(names)
        ctx.record(self.name, checkpoints=len(names))
        if _obs._ENABLED:
            _obs.inc('auto_remat_programs', 1,
                     help='programs the auto_remat pass rewrote to fit '
                          'PADDLE_TPU_HBM_BUDGET_MB')
            _obs.set_gauge('auto_remat_checkpoints', len(names),
                           help='checkpoint boundaries chosen by the last '
                                'auto_remat application')
            _obs.set_gauge('auto_remat_planned_peak_bytes', new_peak,
                           help='predicted peak HBM after the auto_remat '
                                'rewrite')
        return True
