"""``bucket_allreduce``: size-capped, overlap-friendly gradient AllReduce.

Parity target: the reference's ``fuse_all_reduce_op_pass`` +
``alloc_continuous_space_for_grad_pass`` — the machinery behind
``BuildStrategy.fuse_all_reduce_ops`` / ``DistributedStrategy.
fuse_all_reduce_ops``, which this repo documented as no-ops until now.

After ``fleet.distributed_optimizer(...).minimize`` the global block
carries one ``c_allreduce_sum`` per gradient, right after the backward
marker (parallel/fleet.py). Two failure modes at scale:

- left per-grad, the tracer pays one dispatch per parameter and XLA sees
  hundreds of tiny collectives whose per-message latency dominates;
- naively fused into ONE reduction, the whole gradient volume syncs
  tail-synchronously — no byte moves until the last gradient exists, so
  nothing overlaps the backward compute ("Scale MLPerf-0.6 on TPU-v3
  Pods", arxiv 1909.09756, names this the pod-scale killer).

This pass takes the middle: contiguous runs of compatible gradient
``c_allreduce_sum`` ops (same axis / comm_dtype / operand dtype) are split
into buckets capped at ``PADDLE_TPU_ALLREDUCE_BUCKET_MB`` (default 32,
floats accepted) and each bucket becomes one ``c_allreduce_sum_bucket`` op
(parallel/collective.py) sitting at its FIRST member's position —
immediately after the last producer of its gradients — instead of a
single reduction at the tail. XLA's latency-hiding scheduler can then
start each bucket's comm while later program regions still compute.

Bitwise safety: the bucket op is concat -> ONE collective -> split; at
``comm_dtype=f32`` (and in the single-replica identity lowering) that is
bit-identical to the per-grad ops, asserted pass-on/off by
tests/framework/test_bucket_allreduce.py on the MNIST-MLP and
ResNet-block recipes.

Telemetry: ``collective_allreduce_buckets`` counts buckets formed per
pipeline application; per-pass stats land in the PassContext
(``buckets`` / ``bucketed_ops``).
"""
from __future__ import annotations

import os

import numpy as np

from .. import observability as _obs
from ..framework import BACKWARD_OP_TYPE, Operator
from .pass_base import Pass, register_pass

ENV_BUCKET_MB = 'PADDLE_TPU_ALLREDUCE_BUCKET_MB'
DEFAULT_BUCKET_MB = 32.0

# PADDLE_TPU_ALLREDUCE_BUCKET_MB=auto: size the cap from the program's
# predicted gradient bytes (the memory plan's numbers) instead of the
# hand-set 32 MiB — aim at AUTO_TARGET_BUCKETS buckets so 1−1/target of
# the gradient comm can overlap backward compute, floored at 1 MiB so
# tiny models never shatter into latency-dominated messages.
AUTO = 'auto'
AUTO_TARGET_BUCKETS = 4
AUTO_MIN_CAP_BYTES = 1 << 20

BUCKETABLE = ('c_allreduce_sum',)

_DTYPE_BYTES = {'float32': 4, 'float64': 8, 'float16': 2, 'bfloat16': 2,
                'int64': 8, 'int32': 4, 'int8': 1}


def bucket_cap_is_auto():
    raw = os.environ.get(ENV_BUCKET_MB)
    return raw is not None and raw.strip().lower() == AUTO


def auto_cap_bytes(grad_bytes):
    """Cap for `grad_bytes` of gradients under the auto policy."""
    return max(AUTO_MIN_CAP_BYTES,
               -(-int(grad_bytes) // AUTO_TARGET_BUCKETS))


def bucket_cap_bytes(grad_bytes=None):
    """The live bucket cap in bytes. Under ``=auto`` the caller must
    supply the gradients' total predicted bytes (the pass computes them
    from the allreduce operands; ``SpmdTrainStep`` from its replicated
    params); with no `grad_bytes` under auto this returns None — the
    pipeline signature renders that as the ``@auto`` tag."""
    raw = os.environ.get(ENV_BUCKET_MB)
    if raw is not None and raw.strip().lower() == AUTO:
        if grad_bytes is None:
            return None
        return auto_cap_bytes(grad_bytes)
    if raw is None or raw == '':
        mb = DEFAULT_BUCKET_MB
    else:
        try:
            mb = float(raw)
        except ValueError:
            raise ValueError(
                f"{ENV_BUCKET_MB}: expected a number of MiB or 'auto', "
                f"got {raw!r}")
        if mb <= 0:
            raise ValueError(f"{ENV_BUCKET_MB}: must be > 0, got {raw!r}")
    return int(mb * 2 ** 20)


def _op_nbytes(blk, op):
    """Static payload size of one allreduce operand, or None when the var
    shape is unknown (such an op breaks the run — never bucketed)."""
    name = op.inputs.get('x', [None])[0]
    if name is None or not blk.has_var(name):
        return None
    v = blk.var(name)
    if v.shape is None or any(s < 0 for s in v.shape):
        return None
    elems = int(np.prod(v.shape, dtype=np.int64)) if v.shape else 1
    return elems * _DTYPE_BYTES.get(v.dtype, 4), v.dtype


def _compat_key(op, dtype):
    return (op.type, op.attrs.get('axis', 'dp'),
            op.attrs.get('comm_dtype'), dtype)


@register_pass
class BucketAllReducePass(Pass):
    name = 'bucket_allreduce'
    order = 250            # after add+act fusion, before the optimizer fuse

    @staticmethod
    def _enabled(program, ctx):
        bs = ctx.build_strategy
        if bs is not None:
            # executor-level knob wins when a CompiledProgram is in play
            return bool(getattr(bs, 'fuse_all_reduce_ops', False))
        # fleet stamp: DistributedOptimizer.minimize records the
        # DistributedStrategy.fuse_all_reduce_ops decision on the program
        return bool(getattr(program, '_dist_fuse_all_reduce_ops', False))

    def apply_impl(self, program, ctx):
        if not self._enabled(program, ctx):
            return False
        blk = program.global_block()
        ops = blk.ops
        bwd = next((i for i, op in enumerate(ops)
                    if op.type == BACKWARD_OP_TYPE), None)
        if bwd is None:
            return False

        # contiguous runs of compatible gradient allreduces after the
        # marker; contiguity makes the rewrite trivially safe (nothing is
        # interleaved between members) and is what minimize() emits
        runs, cur, cur_key = [], [], None
        for i in range(bwd + 1, len(ops)):
            op = ops[i]
            info = _op_nbytes(blk, op) if op.type in BUCKETABLE else None
            key = _compat_key(op, info[1]) if info is not None else None
            if key is not None and key == cur_key:
                cur.append((i, info[0]))
            else:
                if cur:
                    runs.append(cur)
                cur, cur_key = ([(i, info[0])], key) \
                    if key is not None else ([], None)
        if cur:
            runs.append(cur)

        # =auto sizes the cap from the gradients actually being synced —
        # the same byte figures analysis/plan.gradient_bytes predicts
        total_grad_bytes = sum(nb for run in runs for _, nb in run)
        cap = bucket_cap_bytes(grad_bytes=total_grad_bytes)
        if cap is None:        # auto with nothing bucketable
            return False

        buckets = []           # list of [op index]
        for run in runs:
            acc, acc_bytes = [], 0
            for i, nbytes in run:
                if acc and acc_bytes + nbytes > cap:
                    buckets.append(acc)
                    acc, acc_bytes = [], 0
                acc.append(i)
                acc_bytes += nbytes
            if acc:
                buckets.append(acc)

        fused = {}
        dead = set()
        for bucket in buckets:
            if len(bucket) < 2:
                continue       # a lone allreduce stays as-is
            members = [ops[i] for i in bucket]
            grads = [m.inputs['x'][0] for m in members]
            outs = [m.outputs['Out'][0] for m in members]
            attrs = {k: v for k, v in members[0].attrs.items()}
            bop = Operator(
                blk, 'c_allreduce_sum_bucket',
                inputs={'xs': grads}, outputs={'Out': outs}, attrs=attrs)
            bop._site = members[0]._site
            fused[bucket[0]] = bop
            dead.update(bucket[1:])
        if not fused:
            return False
        blk.ops = [fused.get(i, op) for i, op in enumerate(ops)
                   if i not in dead]
        ctx.record(self.name, buckets=len(buckets),
                   bucketed_ops=sum(len(b) for b in buckets if len(b) >= 2))
        if _obs._ENABLED:
            _obs.inc('collective_allreduce_buckets', len(buckets),
                     help='gradient-allreduce buckets formed by the '
                          'bucket_allreduce IR pass (size cap '
                          'PADDLE_TPU_ALLREDUCE_BUCKET_MB)')
        return True
