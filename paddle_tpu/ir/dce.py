"""Dead-op / dead-var elimination.

The op-list analogue of the reference's graph-level dependency pruning
(``Program._prune`` covers the save-inference path; this pass covers every
execution): an op whose outputs are never read by a later op, never
fetched, and never persisted contributes nothing to the step function —
but the Python tracer still walks it and jax still carries its equations
until XLA's own DCE. Dropping it here removes the cost at every layer.

Liveness roots:
- fetch_names (the caller observes them),
- persistable vars (training state is written back to the Scope),
- the ``__backward__`` marker (it defines the autodiff split; its Loss
  input keeps the forward alive).

A standard reverse walk: keep an op iff any output is live, then mark its
reads — including sub-block reads via ``executor._op_read_names``, so
control-flow branches chained onto the outer env are honored — as live.
Later writers of a var whose value is only read earlier are correctly
dropped (liveness is checked at the op's own position).
"""
from __future__ import annotations

from ..framework import BACKWARD_OP_TYPE
from .pass_base import Pass, register_pass


def _op_read_names(op):
    from ..executor import _op_read_names as impl
    return impl(op)


@register_pass
class DeadCodeEliminationPass(Pass):
    name = 'dce'
    order = 900          # last: sweeps debris the other passes orphaned

    def apply_impl(self, program, ctx):
        blk = program.global_block()
        persist = {v.name for v in program.list_vars() if v.persistable}
        live = set(ctx.fetch_names)
        kept_rev = []
        removed = 0
        for op in reversed(blk.ops):
            outs = op.output_names()
            if (op.type == BACKWARD_OP_TYPE
                    or any(o in live or o in persist for o in outs)):
                kept_rev.append(op)
                live |= _op_read_names(op)
            else:
                removed += 1
        if removed:
            blk.ops = kept_rev[::-1]
        dropped_vars = self._drop_dead_vars(blk, persist, ctx)
        ctx.record(self.name, removed_ops=removed, removed_vars=dropped_vars)
        return bool(removed or dropped_vars)

    @staticmethod
    def _drop_dead_vars(blk, persist, ctx):
        """Remove global-block vars nothing references. Persistables (scope
        state), data vars (feed declarations, incl. '@LEN' companions), and
        fetch targets always stay."""
        used = set(ctx.fetch_names)
        for op in blk.ops:
            used |= _op_read_names(op)
            used |= set(op.output_names())
            # marker attrs name vars the lowering looks up by name
            for attr in ('loss', 'params', 'checkpoints'):
                v = op.attrs.get(attr)
                if isinstance(v, str):
                    used.add(v)
                elif isinstance(v, (list, tuple)):
                    used.update(x for x in v if isinstance(x, str))
        dead = [n for n, v in blk.vars.items()
                if n not in used and n not in persist and not v.is_data]
        for n in dead:
            del blk.vars[n]
        return len(dead)
