"""``fuse_all_optimizer_ops``: N per-param update ops → one multi-tensor op.

Parity target: the reference's fuse_optimizer_ops_pass (fuse_sgd_op_pass /
fuse_momentum_op_pass / fuse_adam_op_pass), gated by the same
``BuildStrategy.fuse_all_optimizer_ops`` knob. After ``minimize``, the
global block tails off with one ``sgd``/``momentum``/``adam`` op per
parameter; tracing them costs O(#params) Python dispatches and the jaxpr
carries the full per-param scalar chains (Adam's bias-correction alone is
~8 equations per parameter). The fused kernels (ops/fused_ops.py) compute
the update once over a flattened bundle — eqn count drops from
O(#params · per-op-eqns) to O(#params · split-cost) with a much smaller
constant, and the tracer dispatches once.

Ops fuse into one group iff they agree on (op type, hyperparameter attrs,
lr input, param dtype) — all float32 only, so the fused bundle math
promotes exactly like the per-param ops and numerics stay bit-identical —
AND the group is independent of everything interleaved between its first
and last member (no read/write overlap either way). Grad-merge programs
keep their updates inside a cond sub-block, which this pass never touches.
"""
from __future__ import annotations

from ..framework import Operator
from .pass_base import Pass, register_pass
from .dce import _op_read_names

# per-param op → (fused op type, input slot → fused variadic slot,
#                 output slot list shared by both)
FUSE_SPECS = {
    'sgd': ('fused_sgd',
            (('param', 'params'), ('grad', 'grads')),
            ('ParamOut',)),
    'momentum': ('fused_momentum',
                 (('param', 'params'), ('grad', 'grads'),
                  ('velocity', 'velocities')),
                 ('ParamOut', 'VelocityOut')),
    'lars_momentum': ('fused_lars_momentum',
                      (('param', 'params'), ('grad', 'grads'),
                       ('velocity', 'velocities')),
                      ('ParamOut', 'VelocityOut')),
    'adam': ('fused_adam',
             (('param', 'params'), ('grad', 'grads'),
              ('moment1', 'moment1s'), ('moment2', 'moment2s'),
              ('beta1_pow', 'beta1_pows'), ('beta2_pow', 'beta2_pows')),
             ('ParamOut', 'Moment1Out', 'Moment2Out', 'Beta1PowOut',
              'Beta2PowOut')),
}


def _attr_sig(op):
    from ..ops.registry import NON_KERNEL_ATTRS
    return tuple(sorted((k, repr(v)) for k, v in op.attrs.items()
                        if k not in NON_KERNEL_ATTRS))


def _is_f32(blk, name):
    return (not blk.has_var(name)) or blk.var(name).dtype == 'float32'


@register_pass
class FuseAllOptimizerOpsPass(Pass):
    name = 'fuse_all_optimizer_ops'
    order = 300

    def enabled(self, ctx):
        bs = ctx.build_strategy
        return bs is not None and getattr(bs, 'fuse_all_optimizer_ops',
                                          False)

    def apply_impl(self, program, ctx):
        if not self.enabled(ctx):
            return False
        blk = program.global_block()
        ops = blk.ops
        groups = {}          # (type, attr sig, lr name) → [op index]
        for i, op in enumerate(ops):
            if op.type not in FUSE_SPECS:
                continue
            if not all(_is_f32(blk, n) for n in op.input_names()):
                continue
            lr = op.inputs.get('lr', [None])[0]
            groups.setdefault((op.type, _attr_sig(op), lr), []).append(i)

        fused_groups = 0
        fused_ops = 0
        dead = set()
        replaced = {}
        for (op_type, _, lr), idxs in sorted(groups.items(),
                                             key=lambda kv: kv[1][0]):
            if len(idxs) < 2 or not self._independent(ops, idxs, lr):
                continue
            fused_type, slot_map, out_slots = FUSE_SPECS[op_type]
            members = [ops[i] for i in idxs]
            inputs = {fused: [m.inputs[per][0] for m in members]
                      for per, fused in slot_map}
            if lr is not None:
                inputs['lr'] = lr
            outputs = {s: [m.outputs[s][0] for m in members]
                       for s in out_slots}
            attrs = {k: v for k, v in members[0].attrs.items()}
            fused = Operator(blk, fused_type, inputs=inputs,
                             outputs=outputs, attrs=attrs)
            fused._site = members[0]._site
            replaced[idxs[0]] = fused
            dead.update(idxs[1:])
            fused_groups += 1
            fused_ops += len(idxs)
        if not fused_groups:
            return False
        blk.ops = [replaced.get(i, op) for i, op in enumerate(ops)
                   if i not in dead]
        ctx.record(self.name, fused_groups=fused_groups,
                   fused_update_ops=fused_ops)
        return True

    @staticmethod
    def _independent(ops, idxs, lr):
        """Group members must be pairwise disjoint (each updates only its
        own param/slots) and nothing interleaved may touch the group's
        vars (the fused op runs at the first member's position)."""
        member = set(idxs)
        reads, writes = set(), set()
        for i in idxs:
            ins, outs = set(ops[i].input_names()), set(ops[i].output_names())
            if (ins - {lr}) & reads or outs & writes:
                return False
            reads |= ins          # lr stays: an interleaved lr write would
            writes |= outs        # give members position-dependent values
        for k in range(idxs[0], idxs[-1] + 1):
            if k in member:
                continue
            o_reads, o_writes = _op_read_names(ops[k]), set(
                ops[k].output_names())
            if (o_reads & writes) or (o_writes & (reads | writes)):
                return False
        return True
