"""Program-level IR pass infrastructure: Pass base class, registry, manager.

The TPU-native analogue of the reference's ``framework/ir`` graph passes
(fuse_elewise_add_act_pass.cc, fuse_optimizer_ops_pass/*, …): instead of
rewriting an SSA graph of OpDesc nodes, a Pass rewrites a ``Program``'s
op list BEFORE ``executor._lower`` traces it into one jax function. Every
Python-level op the passes remove is one less ``_OpRunner`` dispatch per
trace and a handful fewer jaxpr equations per compile — trace+lower time
(and the compile-cache key cost) scale with raw op count, so this is a
pure front-end win; XLA sees a smaller program to fuse, never a different
one numerically.

Determinism contract:

- passes run in ascending ``order`` (ties broken by name), so a pipeline
  built from the same flags always rewrites identically;
- passes NEVER mutate the caller's Program — :meth:`PassManager.apply`
  clones first and rewrites the clone;
- before any rewrite, every global-block op is stamped with a
  ``_rng_salt`` bookkeeping attr carrying its original position, which the
  executor's lowering uses for ``jax.random.fold_in`` — removing or fusing
  ops therefore cannot shift another op's RNG stream, keeping pass-on /
  pass-off numerics bit-identical even through dropout.

Per-pass applied/elapsed counters export through the PR 2 metrics registry
(``ir_pass_applied_total`` / ``ir_pass_seconds`` / ``ir_pass_ops_removed_
total``, labeled by pass) whenever telemetry is enabled.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

from .. import observability as _obs
from ..framework import BACKWARD_OP_TYPE, Program

RNG_SALT_ATTR = '_rng_salt'

_PASS_REGISTRY: Dict[str, 'Pass'] = {}


class PassContext:
    """Immutable-ish facts a pass may consult, plus the stats it fills in."""

    def __init__(self, fetch_names=(), feed_names=(), build_strategy=None,
                 feed_shapes=None):
        self.fetch_names = tuple(fetch_names)
        self.feed_names = tuple(feed_names)
        self.build_strategy = build_strategy
        # name → concrete shape of the run's feeds (executor-supplied);
        # lets shape-sensitive passes (auto_remat) price dynamic dims
        self.feed_shapes = dict(feed_shapes) if feed_shapes else None
        # pass name → {'removed': n, 'fused': n, 'folded': n, ...}
        self.stats: Dict[str, Dict[str, int]] = {}

    def record(self, pass_name, **counts):
        d = self.stats.setdefault(pass_name, {})
        for k, v in counts.items():
            d[k] = d.get(k, 0) + int(v)


class Pass:
    """One deterministic Program rewrite. Subclasses set ``name`` and
    ``order`` and implement :meth:`apply_impl` returning True iff the
    program changed."""

    name: str = None
    # ascending execution order; folding runs before fusion so fused
    # patterns see folded constants, DCE runs last to sweep the debris
    order: int = 100

    def apply(self, program: Program, ctx: PassContext) -> bool:
        t0 = time.perf_counter()
        changed = self.apply_impl(program, ctx)
        if _obs._ENABLED:
            _obs.inc('ir_pass_applied_total', 1,
                     help='IR pass executions by pass name',
                     **{'pass': self.name})
            _obs.observe('ir_pass_seconds', time.perf_counter() - t0,
                         help='wall time per IR pass application',
                         **{'pass': self.name})
        return changed

    def apply_impl(self, program: Program, ctx: PassContext) -> bool:
        raise NotImplementedError


def register_pass(cls):
    """Class decorator: add a Pass subclass to the registry (unique name)."""
    if not cls.name:
        raise ValueError(f'{cls.__name__} has no pass name')
    if cls.name in _PASS_REGISTRY:
        raise ValueError(f'IR pass {cls.name!r} registered twice')
    _PASS_REGISTRY[cls.name] = cls()
    return cls


def get_pass(name: str) -> Pass:
    if name not in _PASS_REGISTRY:
        raise KeyError(f'unknown IR pass {name!r}; registered: '
                       f'{sorted(_PASS_REGISTRY)}')
    return _PASS_REGISTRY[name]


def all_passes():
    return dict(_PASS_REGISTRY)


def stamp_rng_salts(program: Program):
    """Record each global-block op's original position as its RNG salt.

    ``_lower`` folds the step key with this salt (falling back to the live
    op index for unstamped programs), so pass rewrites preserve every
    surviving op's random stream exactly. Idempotent: already-stamped ops
    keep their first salt, which is what makes re-running the pipeline a
    fixpoint."""
    for i, op in enumerate(program.global_block().ops):
        if RNG_SALT_ATTR not in op.attrs:
            op.attrs[RNG_SALT_ATTR] = i


def _make_verifier(opt, ctx):
    """Pass-boundary verification closure, or None when verification is
    off. Called BEFORE any pass runs, so the pre-pipeline error baseline
    describes the pipeline's input; each call then re-verifies `opt` and
    raises on errors the named pass newly introduced."""
    from .. import analysis
    if analysis.verify_level() == 'off':
        return None

    t0 = time.perf_counter()
    pre = analysis.verify_program(opt, fetch_names=ctx.fetch_names,
                                  feed_names=ctx.feed_names)
    state = {'baseline': {
        d.key() for d in analysis.severity_at_least(pre, 'error')}}
    if _obs._ENABLED:
        _obs.observe('program_verify_seconds', time.perf_counter() - t0,
                     help='wall time per static program verification')

    def verify(pass_name):
        t1 = time.perf_counter()
        diags = analysis.assert_verified(
            opt, fetch_names=ctx.fetch_names, feed_names=ctx.feed_names,
            stage='post-pass', pass_name=pass_name,
            baseline=state['baseline'])
        # later passes are measured against this pass's output
        state['baseline'] = {
            d.key() for d in analysis.severity_at_least(diags, 'error')}
        if _obs._ENABLED:
            _obs.inc('program_verify_runs', 1,
                     help='static verifier runs at IR pass boundaries',
                     stage='post-pass')
            _obs.observe('program_verify_seconds',
                         time.perf_counter() - t1,
                         help='wall time per static program verification')

    return verify


class PassManager:
    """Applies a deterministic sequence of passes to a CLONE of a Program."""

    def __init__(self, passes: List[Pass]):
        self.passes = sorted(passes, key=lambda p: (p.order, p.name))

    def apply(self, program: Program, ctx: Optional[PassContext] = None):
        """Returns (optimized_program, ctx). The input Program is untouched;
        when no pass changes anything the clone is still returned (callers
        treat the result as theirs to lower).

        Post-condition (PADDLE_TPU_VERIFY ∈ {passes, full}): after every
        pass that changed the program, the static verifier
        (paddle_tpu/analysis/) re-checks it — a pass that emits an
        inconsistent program raises :class:`ProgramVerificationError`
        naming the pass AT THE PASS BOUNDARY, instead of surfacing as an
        opaque trace error three layers later. The contract is "no NEW
        error-severity diagnostics": a pass is never blamed for problems
        already present in its input (those belong to the 'full'-level
        pre-lowering check)."""
        ctx = ctx or PassContext()
        opt = program.clone()
        # clone() drops non-IR carry attrs the lowering (and the passes
        # themselves — the fleet fuse_all_reduce_ops stamp) read
        for attr in ('_fsdp_axis', '_dist_fuse_all_reduce_ops',
                     '_partition_params', '_partition_specs',
                     '_partition_mesh_axes'):
            if hasattr(program, attr):
                setattr(opt, attr, getattr(program, attr))
        stamp_rng_salts(opt)
        verifier = _make_verifier(opt, ctx) if self.passes else None
        ops_before = len(opt.global_block().ops)
        for p in self.passes:
            changed = p.apply(opt, ctx)
            if changed and verifier is not None:
                verifier(p.name)
        if _obs._ENABLED:
            _obs.inc('ir_pass_pipeline_runs', 1,
                     help='pass-pipeline applications (one per program+shape '
                          'compile-cache miss)')
            _obs.inc('ir_pass_ops_removed_total',
                     ops_before - len(opt.global_block().ops),
                     help='net global-block ops removed by the pass pipeline')
        return opt, ctx

    def names(self):
        return tuple(p.name for p in self.passes)
